"""Offline run-journal analyzer: where did the wall clock go?

    python -m distributed_lion_tpu.cli.run_analyze runs/journal/journal
    python -m distributed_lion_tpu.cli.run_analyze runs/journal \\
        --baseline scripts/last_tpu_measurement.json --json-out report.json

Consumes the JSONL journals ``train/journal.py`` records (one file per
rank, plus rotations), merges multi-host journals onto one wall timeline
(each file's meta record anchors its monotonic clock to ``time.time()`` —
the skew correction), and attributes each interval's measured wall time to
the named buckets:

    device   — the log-cadence device drain (``device_wait`` spans): the
               loop's direct view of device-bound time
    dispatch — host time inside the jitted-call invocations (enqueue, and
               device backpressure once the in-flight queue fills)
    data     — batch fetch + host→device transfer (``data_wait``)
    ckpt     — checkpoint serialize/drain on the step thread (``ckpt/*``;
               committer-thread spans are excluded — they overlap compute)
    logging  — metric assembly + telemetry drain + JSONL writes

plus ``other`` (named spans outside the taxonomy, e.g. ``eval``) and
``unattributed`` (loop bookkeeping no span covers). The identity
``named + other + unattributed == wall`` must close within tolerance
(``closes``); ``coverage`` = named/wall is the acceptance number
(check_evidence's ``journal`` stage requires ≥ 0.95 on a real leg). The
report also ranks the top stall sources by full span name, reports
cross-host step-skew percentiles from the per-rank ``step_log`` events,
and — given ``--baseline`` — diffs the bucket fractions against a
``BENCH_*.json`` / ``last_tpu_measurement.json`` row's
``journal_attribution`` summary to NAME the regressing bucket.

``--serve`` switches to the serve-side view (ISSUE 17): per-request
lifecycle waterfalls (queue → prefill → decode, from the engine's
``serve_finish`` events joined with ``serve/prefill`` spans — every
terminal status, timeouts and failures included) and the drain-cadence
metrics timeline (``serve_metrics``/``serve_stats``/``fleet_stats``/
``slo_breach`` events, serve/metrics.py) — the same numbers the serving
bench banks into serving.json.

Stdlib-only at import (no jax, no package imports), loadable by file path
— the same dependency-light contract as ``train/resilience``'s manifest
verifier, so ``scripts/check_evidence.py`` validates journal artifacts on
boxes without jax.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Optional

# span-name head (before any '/') → attribution bucket. Mirrors the span
# taxonomy documented in train/journal.py; tests/test_journal.py pins that
# the trainer only emits heads this table (plus 'eval') knows.
BUCKET_OF = {
    "device_wait": "device",
    "dispatch": "dispatch",
    "data_wait": "data",
    "ckpt": "ckpt",
    "logging_drain": "logging",
}
NAMED_BUCKETS = ("device", "dispatch", "data", "ckpt", "logging")
# |named + other + unattributed − wall| must stay within this fraction of
# wall (floating accumulation over thousands of spans, nothing more)
CLOSE_TOL_FRAC = 0.01
_JOURNAL_RE = re.compile(r"^journal_rank\d+(\.\d+)?\.jsonl$")


# ------------------------------------------------------------------- loading
def _parse_file(path: str) -> tuple[list, int]:
    """(records, parse_errors) from one journal file. A torn final line
    (crash mid-write) is tolerated silently — that is the journal's
    documented durability unit; any other unparseable line counts as a
    schema error."""
    records: list = []
    errors = 0
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return [], 1
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            if i == len(lines):
                continue  # torn tail: never committed
            errors += 1
            continue
        if not isinstance(rec, dict) or not isinstance(rec.get("t"),
                                                       (int, float)):
            errors += 1
            continue
        records.append(rec)
    return records, errors


def journal_files(directory: str) -> list:
    """Every journal file under ``directory`` (the trainer's
    ``<output_dir>/journal`` layout, or the directory itself when it holds
    the files), rotations included, in (rank, sequence) order."""
    out = []
    for base in (directory, os.path.join(directory, "journal")):
        try:
            names = sorted(os.listdir(base))
        except OSError:
            continue
        out.extend(os.path.join(base, n) for n in names
                   if _JOURNAL_RE.match(n))
        if out:
            break
    return out


def load_journals(directory: str) -> Optional[dict]:
    """Merge a run's journals onto one wall timeline.

    Returns ``{"events": [...], "ranks": [...], "schema_errors": int}`` or
    None when no journal files exist. Every record gains ``tw`` — its wall
    timestamp, ``meta.wall + (t − meta.t)`` per file — which is what makes
    records from hosts with different monotonic epochs comparable (each
    host's monotonic zero is its boot, not an epoch; only the wall anchor
    relates them)."""
    files = journal_files(directory)
    if not files:
        return None
    events: list = []
    errors = 0
    ranks = set()
    for path in files:
        records, errs = _parse_file(path)
        errors += errs
        anchor = next((r for r in records if r.get("kind") == "meta"
                       and isinstance(r.get("wall"), (int, float))), None)
        if anchor is None:
            # a journal file with no clock anchor cannot join the merged
            # timeline — count it against the schema, keep the rest
            errors += 1
            continue
        offset = anchor["wall"] - anchor["t"]
        for r in records:
            r["tw"] = r["t"] + offset
            ranks.add(int(r.get("rank", 0)))
        events.extend(records)
    events.sort(key=lambda r: r["tw"])
    return {"events": events, "ranks": sorted(ranks),
            "schema_errors": errors}


# --------------------------------------------------------------- attribution
def _bucket(name: str) -> Optional[str]:
    return BUCKET_OF.get(name.split("/", 1)[0])


def _step_spans(events: list, rank: int) -> list:
    """This rank's step-thread spans. Any span stamped with a ``thread``
    field ran OFF the step thread (the checkpoint committer, the emulated
    DCN link's ``dcn_wait``) and is excluded: such spans overlap the step
    wall by design and must not count against it."""
    return [r for r in events
            if r.get("kind") == "span" and int(r.get("rank", 0)) == rank
            and isinstance(r.get("dur"), (int, float))
            and not r.get("thread")]


def _leg_window(mine: list, key: str) -> tuple:
    """[start, end] of the MOST RECENT training leg in this rank's
    records. Journals append across process restarts (the sink reopens in
    append mode — a watcher re-fire into the same output_dir is normal
    operation), so taking the first train_start with the last train_end
    would fold the dead inter-run gap into the wall and sink coverage; the
    analyzer reports the latest leg instead. Falls back to the full record
    range when no train_start/train_end markers exist (ring-only bench
    journals always carry them)."""
    starts = [r[key] for r in mine if r.get("name") == "train_start"]
    start = starts[-1] if starts else mine[0][key]
    ends = [r[key] for r in mine
            if r.get("name") == "train_end" and r[key] >= start]
    end = ends[-1] if ends else mine[-1][key]
    return start, end


def attribute(events: list, rank: Optional[int] = None) -> Optional[dict]:
    """Step-wall attribution for one rank (default: the lowest present).

    The window is the MOST RECENT [``train_start``, ``train_end``] leg
    (``_leg_window`` — appended journals from watcher re-fires analyze
    their latest leg, not the union plus the dead gap); every step-thread
    span ending inside it is summed into its bucket. ``unattributed`` is
    the wall the spans do not tile — loop bookkeeping, guard/sentinel host
    reads. ``closes`` is the overlap check: spans that double-count (two
    buckets claiming the same wall) drive ``unattributed`` NEGATIVE, which
    is the one direction the residual arithmetic can actually catch."""
    if not events:
        return None
    ranks = sorted({int(r.get("rank", 0)) for r in events})
    if rank is None:
        rank = ranks[0]
    mine = [r for r in events if int(r.get("rank", 0)) == rank]
    if not mine:
        return None
    key = "tw" if all("tw" in r for r in mine) else "t"
    start, end = _leg_window(mine, key)
    wall = max(end - start, 0.0)
    buckets = {b: 0.0 for b in NAMED_BUCKETS}
    other = 0.0
    for r in _step_spans(mine, rank):
        if not (start <= r[key] <= end + 1e-9):
            continue
        b = _bucket(str(r.get("name", "")))
        if b is None:
            other += r["dur"]
        else:
            buckets[b] += r["dur"]
    named = sum(buckets.values())
    unattributed = wall - named - other
    steps = [r.get("step") for r in mine
             if r.get("name") in ("step_log", "train_start", "train_end")
             and isinstance(r.get("step"), int)
             and start <= r[key] <= end + 1e-9]
    n_steps = (max(steps) - min(steps)) if len(steps) >= 2 else 0
    out = {
        "rank": rank,
        "wall_s": round(wall, 6),
        "steps": n_steps,
        "ms_per_step": (round(wall / n_steps * 1e3, 3) if n_steps else None),
        "buckets": {
            b: {"s": round(s, 6),
                "frac": round(s / wall, 6) if wall else 0.0}
            for b, s in buckets.items()},
        "other_s": round(other, 6),
        "unattributed_s": round(unattributed, 6),
        "coverage": round(named / wall, 6) if wall else 0.0,
    }
    # named + other + unattributed == wall holds by construction (the
    # residual definition), so the IDENTITY cannot fail — what CAN fail is
    # the tiling assumption: overlapping/double-counted spans push the sum
    # of spans past the wall, i.e. unattributed goes negative. That is the
    # direction 'closes' checks (a small negative within tolerance is
    # clock-granularity noise).
    out["closes"] = bool(wall == 0.0
                         or unattributed >= -CLOSE_TOL_FRAC * wall)
    return out


def top_stalls(events: list, rank: Optional[int] = None, k: int = 8) -> list:
    """The top stall sources by full span name (not bucket): total seconds,
    call count, mean ms — the 'name the biggest tax first' list the next
    MFU push starts from. Restricted to the SAME window the attribution
    table covers (the latest training leg), so the two views of the report
    can never disagree about which spans count. ``device_wait`` ranking
    first just means the run is device-bound, which is the healthy case."""
    if not events:
        return []
    ranks = sorted({int(r.get("rank", 0)) for r in events})
    if rank is None:
        rank = ranks[0]
    mine = [r for r in events if int(r.get("rank", 0)) == rank]
    if not mine:
        return []
    key = "tw" if all("tw" in r for r in mine) else "t"
    start, end = _leg_window(mine, key)
    agg: dict = {}
    for r in _step_spans(mine, rank):
        if not (start <= r[key] <= end + 1e-9):
            continue
        name = str(r.get("name", ""))
        s, n = agg.get(name, (0.0, 0))
        agg[name] = (s + r["dur"], n + 1)
    rows = [{"name": name, "s": round(s, 6), "count": n,
             "mean_ms": round(s / n * 1e3, 3)}
            for name, (s, n) in agg.items()]
    rows.sort(key=lambda r: -r["s"])
    return rows[:k]


# membership events the control plane (train/control_plane.py) records:
# the specific worker_left/worker_rejoined pair plus the generic
# membership_transition stream (quarantine/readmit/probation transitions,
# preemption). worker_left/worker_rejoined each ALSO emit a generic twin
# (transition == their own name) so timeline consumers can subscribe to
# one event name; the timeline below keeps the specific record and drops
# the twin.
MEMBERSHIP_EVENTS = ("worker_left", "worker_rejoined",
                     "membership_transition")


def membership_timeline(events: list,
                        rank: Optional[int] = None) -> list:
    """Chronological worker leave/join/quarantine timeline from the
    control plane's journal events — surfaced alongside step attribution
    so a step-time regression and the membership change that caused it
    (a W−1 degraded phase votes on a smaller quorum; a rejoin heals
    momentum at the boundary) read off one report. Every rank's trainer
    runs its own plane and journals the same global transition, so with
    ``rank=None`` identical rows from different ranks collapse to one
    (like step_skew, membership is cross-rank-redundant by design)."""
    rows, seen = [], set()
    for r in events:
        if r.get("kind") != "event" or r.get("name") not in MEMBERSHIP_EVENTS:
            continue
        if rank is not None and r.get("rank") != rank:
            continue
        if (r.get("name") == "membership_transition"
                and r.get("transition") in ("worker_left",
                                            "worker_rejoined")):
            continue  # the specific record carries this transition
        row = {"event": r["name"]}
        for k in ("step", "worker", "cause", "transition", "alive",
                  "world"):
            if k in r:
                row[k] = r[k]
        key = tuple(sorted(row.items()))
        if key in seen:
            continue  # the same transition journaled by another rank
        seen.add(key)
        rows.append(row)
    rows.sort(key=lambda r: (r.get("step", 0),
                             0 if r["event"] == "worker_left" else 1))
    return rows


# serve-side replica lifecycle events (serve/replica_plane.py): the
# fleet's replica leave/drain/slow/rejoin transitions plus per-request
# migration records — the serving twin of MEMBERSHIP_EVENTS, surfaced as
# its own timeline beside the membership one (a serve journal and a train
# journal never mix ranks, but one analyzer reads both).
REPLICA_EVENTS = ("replica_left", "replica_rejoined", "replica_draining",
                  "replica_slow", "request_migrated", "request_failed",
                  "request_timeout")


def replica_timeline(events: list, rank: Optional[int] = None) -> list:
    """Chronological replica lifecycle + request-migration timeline from
    the fleet's journal events — a crash, the migrations it caused, and
    the rejoin that restored capacity read off one report, the way the
    membership timeline reads for training workers."""
    rows, seen = [], set()
    for r in events:
        if r.get("kind") != "event" or r.get("name") not in REPLICA_EVENTS:
            continue
        if rank is not None and r.get("rank") != rank:
            continue
        row = {"event": r["name"]}
        for k in ("tick", "replica", "req_id", "from_replica", "to_replica",
                  "cause", "attempt", "attempts", "committed", "residents",
                  "latency_ticks", "alive", "world"):
            if k in r:
                row[k] = r[k]
        key = tuple(sorted(row.items()))
        if key in seen:
            continue
        seen.add(key)
        rows.append(row)
    rows.sort(key=lambda r: (r.get("tick", 0),
                             0 if r["event"].startswith("replica") else 1))
    return rows


def step_skew(events: list) -> Optional[dict]:
    """Cross-host step-skew percentiles from the per-rank ``step_log``
    events on the merged wall timeline: for every step logged by more than
    one rank, the spread max(tw) − min(tw) is how far apart the hosts
    reached the same step. None on single-rank journals (nothing to
    compare)."""
    by_step: dict = {}
    for r in events:
        if r.get("name") == "step_log" and isinstance(r.get("step"), int) \
                and "tw" in r:
            # latest occurrence per (step, rank) wins: appended journals
            # from watcher re-fires re-log the same steps, and only the
            # latest leg's arrival times describe one coherent run
            by_step.setdefault(r["step"], {})[int(r.get("rank", 0))] = r["tw"]
    spreads = sorted(max(ts.values()) - min(ts.values())
                     for ts in by_step.values() if len(ts) > 1)
    if not spreads:
        return None

    def pct(p: float) -> float:
        return spreads[min(int(p * len(spreads)), len(spreads) - 1)]

    return {"steps_compared": len(spreads),
            "p50_s": round(pct(0.50), 6),
            "p95_s": round(pct(0.95), 6),
            "max_s": round(spreads[-1], 6)}


# ------------------------------------------------------------- baseline diff
def load_baseline_attribution(path: str) -> Optional[dict]:
    """The ``journal_attribution`` summary from a bench artifact — a
    ``BENCH_*.json`` capture (summary under ``parsed``) or a bare bench row
    (``last_tpu_measurement.json``). None when the artifact predates the
    journal (bench rows only carry the summary from ISSUE 7 on)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(doc, dict):
        return None
    for node in (doc, doc.get("parsed") or {}):
        att = node.get("journal_attribution")
        if isinstance(att, dict) and isinstance(att.get("buckets"), dict):
            return att
    return None


def diff_vs_baseline(att: dict, baseline: dict) -> dict:
    """Per-bucket fraction deltas vs a baseline attribution; the bucket
    whose share GREW the most is named as the regressing one (a perf
    regression shows up as some tax eating a larger share of the wall)."""
    deltas = {}
    for b in NAMED_BUCKETS:
        cur = (att["buckets"].get(b) or {}).get("frac", 0.0)
        base = (baseline.get("buckets", {}).get(b) or {}).get("frac", 0.0)
        deltas[b] = round(cur - base, 6)
    worst = max(deltas, key=lambda b: deltas[b])
    return {"frac_delta": deltas,
            "regressing_bucket": worst if deltas[worst] > 0 else None}


# -------------------------------------------------------------------- driver
def analyze_dir(directory: str, rank: Optional[int] = None,
                baseline: Optional[str] = None) -> Optional[dict]:
    """The full report dict for a run directory, or None when it holds no
    journal (check_evidence's ``journal`` stage calls exactly this)."""
    loaded = load_journals(directory)
    if loaded is None:
        return None
    att = attribute(loaded["events"], rank)
    report = {
        "directory": directory,
        "ranks": loaded["ranks"],
        "schema_errors": loaded["schema_errors"],
        "attribution": att,
        "top_stalls": top_stalls(loaded["events"], rank),
        "step_skew": step_skew(loaded["events"]),
        "membership": membership_timeline(loaded["events"], rank),
        "replicas": replica_timeline(loaded["events"], rank),
    }
    if baseline:
        base_att = load_baseline_attribution(baseline)
        report["baseline"] = baseline
        report["baseline_diff"] = (diff_vs_baseline(att, base_att)
                                   if att and base_att else None)
    return report


# ------------------------------------------------------------- serve mode
def serve_waterfalls(events: list, rank: Optional[int] = None) -> list:
    """Per-request lifecycle rows from the serve journal: one row per
    terminal ``serve_finish`` event (every status — timeout/failed rows
    are exactly the ones an incident report needs), joined with the
    request's ``serve/prefill`` span when it reached one. Tick-domain
    columns come from the engine's request clocks (serve/metrics.
    RequestTimes); wall columns appear when the metrics plane was on."""
    if rank is None:
        ranks = {int(r.get("rank", 0)) for r in events}
        rank = min(ranks) if ranks else 0
    mine = [r for r in events if int(r.get("rank", 0)) == rank]
    prefills: dict = {}
    for r in mine:
        if (r.get("kind") == "span" and r.get("name") == "serve/prefill"
                and "req_id" in r and isinstance(r.get("dur"),
                                                 (int, float))):
            prefills.setdefault(str(r["req_id"]), r)
    rows = []
    for r in mine:
        if r.get("kind") != "event" or r.get("name") != "serve_finish":
            continue
        rid = str(r.get("req_id"))
        row = {"req_id": rid, "reason": r.get("reason", "?")}
        for k in ("queue_ticks", "ttft_ticks", "decode_ticks", "ttft_ms"):
            if isinstance(r.get(k), (int, float)):
                row[k] = r[k]
        p = prefills.get(rid)
        if p is not None:
            row["prefill_ms"] = float(p["dur"]) * 1e3
            row["prompt_len"] = p.get("prompt_len")
            row["shared"] = p.get("shared")
        row["finish_tw"] = r.get("tw")
        rows.append(row)
    rows.sort(key=lambda x: (x.get("finish_tw") or 0.0, x["req_id"]))
    return rows


def serve_metrics_timeline(events: list,
                           rank: Optional[int] = None) -> list:
    """The drain-cadence fleet/engine metrics timeline: one row per
    ``serve_metrics`` journal event (sketch summaries + gauges + SLO
    counters, already flat strict-JSON fields) plus the matching
    ``serve_stats``/``fleet_stats`` counter snapshots."""
    if rank is None:
        ranks = {int(r.get("rank", 0)) for r in events}
        rank = min(ranks) if ranks else 0
    out = []
    for r in events:
        if int(r.get("rank", 0)) != rank or r.get("kind") != "event":
            continue
        if r.get("name") in ("serve_metrics", "serve_stats",
                             "fleet_stats", "serve_fleet_metrics",
                             "serve_done", "slo_breach"):
            row = {k: v for k, v in r.items()
                   if k not in ("kind", "t", "rank")}
            row["event"] = row.pop("name")
            out.append(row)
    return out


def serve_report(directory: str, rank: Optional[int] = None
                 ) -> Optional[dict]:
    """The --serve report: waterfalls + metrics timeline, or None when
    the directory holds no journal."""
    loaded = load_journals(directory)
    if loaded is None:
        return None
    return {
        "directory": directory,
        "ranks": loaded["ranks"],
        "schema_errors": loaded["schema_errors"],
        "requests": serve_waterfalls(loaded["events"], rank),
        "timeline": serve_metrics_timeline(loaded["events"], rank),
        "replicas": replica_timeline(loaded["events"], rank),
    }


_WATERFALL_MAX_ROWS = 40
_WATERFALL_MAX_BAR = 48


def _waterfall_bar(row: dict) -> str:
    """Tick-domain lifecycle bar: '.' per queued tick, 'P' for the
    prefill/first-token tick, '#' per decode tick — truncated with '~'
    past the display budget (long decodes must not wrap the report)."""
    q = int(row.get("queue_ticks", 0) or 0)
    d = int(row.get("decode_ticks", 0) or 0)
    bar = "." * q + ("P" if "ttft_ticks" in row else "") + "#" * d
    if len(bar) > _WATERFALL_MAX_BAR:
        bar = bar[:_WATERFALL_MAX_BAR - 1] + "~"
    return bar


def render_serve(report: dict) -> str:
    lines = [f"serve journal: {report['directory']} "
             f"(ranks {report['ranks']}, "
             f"{report['schema_errors']} schema error(s))"]
    rows = report.get("requests") or []
    by_reason: dict = {}
    for r in rows:
        by_reason[r["reason"]] = by_reason.get(r["reason"], 0) + 1
    lines.append(f"{len(rows)} request(s): " + ", ".join(
        f"{k}={v}" for k, v in sorted(by_reason.items())) if rows
        else "no serve_finish events (was the run journaled with "
             "--journal_dir?)")
    if rows:
        lines.append("request waterfalls (queue '.' -> prefill 'P' -> "
                     "decode '#'; ticks):")
        for r in rows[:_WATERFALL_MAX_ROWS]:
            cols = [f"  {r['req_id']:<8}"]
            cols.append(f"q{r.get('queue_ticks', '?'):>4}")
            cols.append(f"d{r.get('decode_ticks', '?'):>4}")
            cols.append(f"ttft {r['ttft_ms']:7.1f} ms"
                        if isinstance(r.get("ttft_ms"), (int, float))
                        else "ttft       -")
            cols.append(f"{r['reason']:<8}")
            cols.append(_waterfall_bar(r))
            lines.append(" ".join(cols))
        if len(rows) > _WATERFALL_MAX_ROWS:
            lines.append(f"  ... {len(rows) - _WATERFALL_MAX_ROWS} more "
                         "(full set in --json-out)")
    tl = report.get("timeline") or []
    if tl:
        lines.append("metrics timeline (drain cadence):")
        for row in tl:
            ev = row["event"]
            if ev == "serve_metrics":
                lines.append(
                    f"  tick {row.get('tick', '?'):>6}  "
                    f"ttft p50/p99 {row.get('ttft_ms_p50', 0):.1f}/"
                    f"{row.get('ttft_ms_p99', 0):.1f} ms  "
                    f"tok p99 {row.get('tok_ms_p99', 0):.1f} ms  "
                    f"queue {row.get('gauge_queue_depth', 0):.0f}  "
                    f"slots {row.get('gauge_active_slots', 0):.0f}  "
                    f"pages {row.get('gauge_pages_allocated', 0):.0f}")
            elif ev == "slo_breach":
                lines.append(
                    f"  tick {row.get('tick', '?'):>6}  SLO BREACH: "
                    f"burn rate {row.get('burn_rate', 0):.2f} "
                    f"({row.get('window_violations', '?')}/"
                    f"{row.get('window', '?')} in window)")
            else:
                keep = {k: v for k, v in row.items()
                        if k not in ("event", "tw") and
                        isinstance(v, (int, float))}
                short = ", ".join(f"{k}={v}" for k, v in
                                  sorted(keep.items())[:8])
                lines.append(f"  {ev}: {short}")
    if report.get("replicas"):
        lines.append("replica timeline: "
                     f"{len(report['replicas'])} event(s) "
                     "(full view without --serve)")
    return "\n".join(lines)


def _fmt_s(v: float) -> str:
    return f"{v * 1e3:8.1f} ms" if v < 10 else f"{v:8.2f} s "


def render(report: dict) -> str:
    lines = [f"run journal: {report['directory']} "
             f"(ranks {report['ranks']}, "
             f"{report['schema_errors']} schema error(s))"]
    att = report.get("attribution")
    if att:
        lines.append(
            f"rank {att['rank']}: wall {att['wall_s']:.2f}s over "
            f"{att['steps']} step(s)"
            + (f" ({att['ms_per_step']:.1f} ms/step)"
               if att.get("ms_per_step") else "")
            + f" — coverage {att['coverage'] * 1e2:.1f}% "
            f"({'closes' if att['closes'] else 'DOES NOT CLOSE'})")
        for b in NAMED_BUCKETS:
            v = att["buckets"][b]
            lines.append(f"  {b:<10} {_fmt_s(v['s'])}  "
                         f"{v['frac'] * 1e2:5.1f}%")
        lines.append(f"  {'other':<10} {_fmt_s(att['other_s'])}  "
                     f"{att['other_s'] / att['wall_s'] * 1e2:5.1f}%"
                     if att["wall_s"] else "  other      0")
        lines.append(
            # negative unattributed = overlapping spans (the 'closes'
            # failure); show it, never clamp the symptom away
            f"  {'unattrib.':<10} {att['unattributed_s'] * 1e3:8.1f} ms")
    if report.get("top_stalls"):
        lines.append("top stall sources:")
        for row in report["top_stalls"]:
            lines.append(f"  {row['name']:<22} {_fmt_s(row['s'])}  "
                         f"x{row['count']} (mean {row['mean_ms']:.2f} ms)")
    if report.get("membership"):
        lines.append("membership timeline:")
        for r in report["membership"]:
            what = r.get("transition") or r["event"]
            who = (f"worker {r['worker']}" if "worker" in r else "process")
            quorum = (f"  [alive {r['alive']}/{r['world']}]"
                      if "alive" in r and "world" in r else "")
            lines.append(f"  step {r.get('step', '?'):>6}  {who}: {what}"
                         + (f" ({r['cause']})" if r.get("cause") else "")
                         + quorum)
    if report.get("replicas"):
        lines.append("replica timeline:")
        for r in report["replicas"]:
            if "req_id" in r:
                # request events first: engine-side timeouts carry BOTH a
                # req_id and the replica it happened on — the incident
                # report must say WHICH request, not just where
                src = r.get("from_replica", r.get("replica", "?"))
                dst = (f" -> {r['to_replica']}" if "to_replica" in r else "")
                who = f"request {r['req_id']} (replica {src}{dst})"
            elif "replica" in r:
                who = f"replica {r['replica']}"
            else:
                who = "fleet"
            extra = []
            if r.get("cause"):
                extra.append(r["cause"])
            if "committed" in r:
                extra.append(f"{r['committed']} committed")
            if "residents" in r:
                extra.append(f"{r['residents']} resident(s)")
            quorum = (f"  [alive {r['alive']}/{r['world']}]"
                      if "alive" in r and "world" in r else "")
            lines.append(f"  tick {r.get('tick', '?'):>6}  {who}: "
                         f"{r['event']}"
                         + (f" ({', '.join(extra)})" if extra else "")
                         + quorum)
    skew = report.get("step_skew")
    if skew:
        lines.append(f"cross-host step skew over {skew['steps_compared']} "
                     f"step(s): p50 {skew['p50_s'] * 1e3:.1f} ms, "
                     f"p95 {skew['p95_s'] * 1e3:.1f} ms, "
                     f"max {skew['max_s'] * 1e3:.1f} ms")
    if "baseline" in report:
        diff = report.get("baseline_diff")
        if diff is None:
            lines.append(f"baseline {report['baseline']}: no "
                         "journal_attribution to diff against")
        else:
            worst = diff["regressing_bucket"]
            lines.append(
                f"vs baseline {report['baseline']}: "
                + (f"regressing bucket = {worst} "
                   f"(+{diff['frac_delta'][worst] * 1e2:.1f}% of wall)"
                   if worst else "no bucket grew its share"))
            lines.append("  frac deltas: " + ", ".join(
                f"{b} {d:+.3f}" for b, d in diff["frac_delta"].items()))
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        description="offline run-journal analyzer (stdlib-only)")
    ap.add_argument("directory", help="run directory holding "
                    "journal_rank*.jsonl (or its parent)")
    ap.add_argument("--rank", type=int, default=None,
                    help="attribute this rank (default: lowest present)")
    ap.add_argument("--baseline", default=None,
                    help="BENCH_*.json / last_tpu_measurement.json to diff "
                         "bucket fractions against")
    ap.add_argument("--json-out", default=None,
                    help="also write the full report as strict JSON")
    ap.add_argument("--serve", action="store_true",
                    help="serve-side view: per-request waterfalls "
                         "(queue->prefill->decode from serve_finish + "
                         "serve/prefill records) and the drain-cadence "
                         "metrics timeline, instead of step attribution")
    args = ap.parse_args(argv)
    if args.serve:
        report = serve_report(args.directory, rank=args.rank)
        if report is None:
            print(f"no journal files under {args.directory}",
                  file=sys.stderr)
            return 1
        print(render_serve(report))
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump(report, f, indent=1, allow_nan=False)
                f.write("\n")
        # the leg closed iff at least one request reached a terminal
        # record — a journaled serve run with zero serve_finish events
        # means the workload silently never finished
        return 0 if report["requests"] else 1
    report = analyze_dir(args.directory, rank=args.rank,
                         baseline=args.baseline)
    if report is None:
        print(f"no journal files under {args.directory}", file=sys.stderr)
        return 1
    print(render(report))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=1, allow_nan=False)
            f.write("\n")
    att = report.get("attribution")
    if att is None or not att["closes"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
