"""SFT entry point — the reference's ``sft_llama2.py`` workload (Llama +
QLoRA + packed SFT, /root/reference/sft_llama2.py, README.md:41-63) rebuilt
TPU-native.

Maps the reference's pieces:
- 4-bit NF4 base + bf16 compute (:141-153) → ``--quant nf4`` (ops/quant);
- LoRA q/v r=8 α=16 (:44-51)            → ``--lora_r/--lora_alpha``;
- ConstantLengthDataset packing (:122-137) → data/sft.constant_length_batches;
- chars_token_ratio estimation (:62-75)  → logged before training;
- guards (:53-59): packing×group_by_length mutually exclusive, gradient
  checkpointing rejected with PEFT (we remat per-block regardless — the
  guard is kept for CLI parity and prints why it's moot here);
- --lion/--async_grad optimizer wiring (:163-181);
- post-train merge_and_unload + save merged (:183-199) → models/lora.merge_lora
  → utils/serialization.save_pytree.

Data: ``--dataset jsonl:<path>`` with stack-exchange-paired-style records
({"question", "response_j"}), or ``synthetic`` Q/A pairs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class SFTArguments:
    """sft_llama2.py ScriptArguments (:20-40) equivalents."""

    model_name: str = "llama2_7b"  # llama2_7b | llama3_8b | tiny
    model_path: Optional[str] = None  # local HF Llama checkpoint → finetune a
    # PRETRAINED base, the reference's from_pretrained path
    # (sft_llama2.py:141-154); overrides model_name's architecture
    dataset: str = "synthetic"     # synthetic | jsonl:<path>
    seq_length: int = 1024
    size_valid_set: int = 64
    num_train_samples: int = 512   # synthetic corpus size
    quant: str = "none"            # none | int8 | nf4  (reference: nf4)
    quant_block: Optional[int] = None  # quant block size override (elements;
    # defaults: nf4 64, int8 256). Shrink when a small model's projections
    # must shard under --tensor_parallel (last dim / block % tp == 0).
    lora_r: int = 8
    lora_alpha: int = 16
    lora_dropout: float = 0.05  # adapter-branch dropout (sft_llama2.py:48)
    packing: bool = True
    group_by_length: bool = False
    gradient_checkpointing: bool = False
    attn_impl: str = "auto"  # ops.attention: auto | xla | xla_bf16 | flash | splash
    seq_impl: str = "ring"   # under --seq_parallel: ring | ulysses
    tokenizer_name: Optional[str] = None
    adapter_path: Optional[str] = None  # start from a PEFT adapter
    # checkpoint (adapter_config.json + adapter_model.safetensors) instead
    # of fresh lora_init — models/hf_import.peft_to_lora
    adapter_output: Optional[str] = None  # save the trained LoRA adapters
    # as a HF PEFT checkpoint directory (adapter_model.safetensors +
    # adapter_config.json — PeftModel.from_pretrained-loadable; the
    # reference's pre-merge save_model artifact, sft_llama2.py:183-190)
    merged_output: Optional[str] = None  # save the LoRA-merged model here:
    # a *.npz path → flat save_pytree archive (cli/run_generate's format);
    # any other path → an HF save_pretrained directory
    # (LlamaForCausalLM.from_pretrained-loadable, models/hf_export)


def _sp_head_loss(effective, batch, model_cfg, train_cfg, tp_axis=None):
    """Seq-parallel SFT loss over the (possibly adapted/quantized) effective
    params — ONE dispatch point for the dense vs chunked-vocab head under
    ``--seq_parallel``, with or without a tensor axis. ``--vocab_chunks``
    streams the lm_head per shard (ops/xent.chunked_clm_loss_seq_parallel:
    the [B, T/sp, V] logits never materialize and the shard-boundary label
    ppermute is shared with the dense path's protocol)."""
    from distributed_lion_tpu.models.llama import llama_apply, llama_hidden
    from distributed_lion_tpu.models.loss import clm_loss_seq_parallel
    from distributed_lion_tpu.parallel.mesh import SEQ_AXIS

    if train_cfg.vocab_chunks > 0:
        from distributed_lion_tpu.ops.quant import maybe_dequant
        from distributed_lion_tpu.ops.xent import chunked_clm_loss_seq_parallel

        hidden = llama_hidden(effective, batch, model_cfg,
                              tp_axis=tp_axis, seq_axis=SEQ_AXIS)
        emb = maybe_dequant(effective["lm_head"], model_cfg.compute_dtype)
        return chunked_clm_loss_seq_parallel(
            hidden, emb, batch, train_cfg.vocab_chunks, SEQ_AXIS,
            emb_layout="dv")
    logits = llama_apply(effective, batch, model_cfg,
                         tp_axis=tp_axis, seq_axis=SEQ_AXIS)
    return clm_loss_seq_parallel(logits, batch, SEQ_AXIS)


def main(argv=None):
    from distributed_lion_tpu.utils.argparsing import parse_dataclasses

    script_args, train_cfg = parse_dataclasses((SFTArguments, _train_cfg_cls()), argv)

    # Reference guards (sft_llama2.py:53-59).
    if script_args.packing and script_args.group_by_length:
        raise ValueError("Cannot use both packing and group by length")
    if script_args.gradient_checkpointing:
        raise ValueError(
            "gradient_checkpointing with LoRA is rejected for parity with the "
            "reference (sft_llama2.py:56-59); note this framework remats every "
            "block regardless, so the memory benefit is already in place"
        )

    import jax
    import jax.numpy as jnp

    from distributed_lion_tpu.cli.run_clm import build_mesh
    from distributed_lion_tpu.data.sft import (
        chars_token_ratio,
        constant_length_batches,
        load_pairs_jsonl,
        synthetic_qa_pairs,
    )
    from distributed_lion_tpu.data.tokenizer import load_tokenizer
    from distributed_lion_tpu.models.llama import LlamaConfig, llama_apply, llama_init
    from distributed_lion_tpu.models.lora import (
        LoraConfig,
        apply_adapters,
        lora_init,
        merge_lora,
    )
    from distributed_lion_tpu.ops.quant import quantize_tree
    from distributed_lion_tpu.train.loop import Trainer
    from distributed_lion_tpu.utils.serialization import save_pytree

    sp = train_cfg.seq_parallel
    if sp > 1:
        # long-context SFT: packed rows sharded over tokens, ring attention
        # over the 'seq' axis; boundary labels ride a ppermute
        # (models/loss.clm_loss_seq_parallel)
        if not script_args.packing:
            raise NotImplementedError(
                "--seq_parallel needs --packing: padded/masked per-example "
                "rows are not wired across sequence shards"
            )
    mesh = build_mesh(train_cfg.tensor_parallel, sp)
    tok = load_tokenizer(script_args.tokenizer_name)

    if script_args.dataset == "synthetic":
        records = synthetic_qa_pairs(script_args.num_train_samples + script_args.size_valid_set)
        valid = records[: script_args.size_valid_set]
        train = records[script_args.size_valid_set:]
    elif script_args.dataset.startswith("jsonl:"):
        train, valid = load_pairs_jsonl(
            script_args.dataset[len("jsonl:"):], size_valid_set=script_args.size_valid_set
        )
    else:
        raise ValueError(f"unknown dataset spec {script_args.dataset!r}")

    ratio = chars_token_ratio(train, tok)
    print(f"[run_sft] chars/token ratio: {ratio:.2f} over {min(len(train), 400)} samples")

    if script_args.model_path:
        from distributed_lion_tpu.models.hf_import import llama_from_hf

        base_params, model_cfg = llama_from_hf(script_args.model_path)
        print(f"[run_sft] loaded pretrained Llama from {script_args.model_path}: "
              f"{model_cfg.n_layer}L d={model_cfg.d_model} vocab={model_cfg.vocab_size}")
        if tok.vocab_size > model_cfg.vocab_size:
            raise ValueError(
                f"tokenizer vocab {tok.vocab_size} exceeds the checkpoint's "
                f"{model_cfg.vocab_size}; pass the checkpoint's own tokenizer"
            )
    else:
        model_cfg = LlamaConfig.named(script_args.model_name,
                                      vocab_size=max(tok.vocab_size, 259))
    model_cfg = dataclasses.replace(model_cfg, attn_impl=script_args.attn_impl,
                                    seq_impl=script_args.seq_impl)
    if script_args.seq_length > model_cfg.n_ctx:
        script_args.seq_length = model_cfg.n_ctx
    if sp > 1 and script_args.seq_length % sp:
        # checked AFTER the n_ctx clamp so the validated value is the one
        # the packed rows actually use
        raise ValueError(
            f"--seq_length {script_args.seq_length} (after the n_ctx clamp) "
            f"must divide evenly over the {sp}-way seq axis"
        )
    train_cfg.block_size = script_args.seq_length

    if not script_args.model_path:
        base_params = llama_init(jax.random.key(train_cfg.seed), model_cfg)
    if script_args.quant != "none":
        print(f"[run_sft] quantizing frozen base to {script_args.quant}")
        base_params = quantize_tree(base_params, script_args.quant,
                                    block=script_args.quant_block)

    if script_args.adapter_path:
        # continue training a PEFT checkpoint (ours via --adapter_output, or
        # one trained by the torch/peft stack) — r/alpha/targets come from
        # its adapter_config.json, overriding --lora_r/--lora_alpha
        from distributed_lion_tpu.models.hf_import import peft_to_lora

        adapters, lora_cfg = peft_to_lora(script_args.adapter_path, model_cfg)
        print(f"[run_sft] resumed PEFT adapter from {script_args.adapter_path} "
              f"(r={lora_cfg.r} alpha={lora_cfg.alpha})")
    else:
        lora_cfg = LoraConfig(r=script_args.lora_r, alpha=script_args.lora_alpha,
                              dropout=script_args.lora_dropout)
        adapters = lora_init(jax.random.key(train_cfg.seed + 1), base_params, lora_cfg)
    n_adapter = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(adapters))
    print(f"[run_sft] LoRA adapters: {len(adapters)} sites, {n_adapter/1e3:.1f}k trainable params")

    from distributed_lion_tpu.models.loss import clm_loss_and_metrics

    def _split_batch(batch):
        # packed: plain [B, T] token array; non-packed: {"tokens", "mask"}
        if isinstance(batch, dict):
            return batch["tokens"], batch["mask"]
        return batch, None

    def _head_loss(effective, tokens, mask, tp_axis=None):
        """Dense or chunked-vocab CLM loss over the (possibly adapted)
        effective params — --vocab_chunks streams the lm_head projection
        (ops/xent) so the [B,T,V] logits are never materialized (V is 32k
        for Llama-2, 128k for Llama-3-class configs)."""
        if train_cfg.vocab_chunks > 0:
            from distributed_lion_tpu.models.llama import llama_hidden
            from distributed_lion_tpu.ops.quant import maybe_dequant
            from distributed_lion_tpu.ops.xent import chunked_clm_loss_and_metrics

            hidden = llama_hidden(effective, tokens, model_cfg, tp_axis=tp_axis)
            # lm_head stays in its [d, V] matmul layout — ops/xent slices
            # columns per chunk, no transposed copy of the head
            emb = maybe_dequant(effective["lm_head"], model_cfg.compute_dtype)
            return chunked_clm_loss_and_metrics(
                hidden, emb, tokens, train_cfg.vocab_chunks, mask,
                emb_layout="dv")
        logits = llama_apply(effective, tokens, model_cfg, tp_axis=tp_axis)
        return clm_loss_and_metrics(logits, tokens, mask)

    tp = train_cfg.tensor_parallel
    if tp > 1:
        # frozen base sharded over the tensor axis, threaded through the
        # train step as a live argument; adapters shard with their targets
        # (models/lora.lora_adapter_specs), replicated factors get the
        # copy_to_tp_region gradient boundary inside apply_adapters.
        from distributed_lion_tpu.models.lora import lora_adapter_specs
        from distributed_lion_tpu.parallel.mesh import TENSOR_AXIS
        from distributed_lion_tpu.parallel.tensor_parallel import (
            llama_param_specs,
            validate_tp,
        )

        validate_tp(model_cfg, tp, "llama")
        base_specs = llama_param_specs(model_cfg)
        if script_args.quant != "none":
            # the shaped QuantizedTensor layout shards with the dense specs;
            # fail fast with the leaf path if block alignment doesn't allow it
            from distributed_lion_tpu.ops.quant import validate_quant_tp

            validate_quant_tp(base_params, base_specs, tp, TENSOR_AXIS)
        adapter_specs = lora_adapter_specs(adapters, base_specs, TENSOR_AXIS)

        if sp > 1:
            # tp x sp: long-context QLoRA SFT — base weights sharded over
            # 'tensor', packed rows' tokens sharded over 'seq' (ring
            # attention), one vote world over 'data'. Gradients: the f/g
            # custom-vjp pair keeps per-tensor-rank adapter grads exact,
            # and the train loop psums grads over the seq axis.
            from jax.sharding import PartitionSpec as P

            from distributed_lion_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS

            def loss_fn(params, frozen, batch, dropout_key):
                effective = apply_adapters(frozen, params, lora_cfg,
                                           tp_axis=TENSOR_AXIS,
                                           base_specs=base_specs,
                                           dropout_key=dropout_key)
                return _sp_head_loss(effective, batch, model_cfg, train_cfg,
                                     tp_axis=TENSOR_AXIS)

            loss_fn._vocab_chunked = True
            trainer = Trainer(train_cfg, mesh, apply_fn=None, params=adapters,
                              param_specs=adapter_specs, loss_fn=loss_fn,
                              frozen_params=base_params,
                              frozen_specs=base_specs,
                              batch_spec=P(DATA_AXIS, SEQ_AXIS))
        else:
            def loss_fn(params, frozen, batch, dropout_key):
                tokens, mask = _split_batch(batch)
                effective = apply_adapters(frozen, params, lora_cfg,
                                           tp_axis=TENSOR_AXIS,
                                           base_specs=base_specs,
                                           dropout_key=dropout_key)
                return _head_loss(effective, tokens, mask, tp_axis=TENSOR_AXIS)

            loss_fn._vocab_chunked = True
            trainer = Trainer(train_cfg, mesh, apply_fn=None, params=adapters,
                              param_specs=adapter_specs, loss_fn=loss_fn,
                              frozen_params=base_params, frozen_specs=base_specs)
    elif sp > 1:
        from jax.sharding import PartitionSpec as P

        from distributed_lion_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS

        def loss_fn(params, batch, dropout_key):
            # batch is this shard's contiguous token chunk [B, T/sp]
            effective = apply_adapters(base_params, params, lora_cfg,
                                       dropout_key=dropout_key)
            return _sp_head_loss(effective, batch, model_cfg, train_cfg)

        loss_fn._vocab_chunked = True
        trainer = Trainer(train_cfg, mesh, apply_fn=None, params=adapters,
                          loss_fn=loss_fn,
                          batch_spec=P(DATA_AXIS, SEQ_AXIS))
    else:
        def loss_fn(params, batch, dropout_key):
            tokens, mask = _split_batch(batch)
            effective = apply_adapters(base_params, params, lora_cfg,
                                       dropout_key=dropout_key)
            return _head_loss(effective, tokens, mask)

        loss_fn._vocab_chunked = True
        trainer = Trainer(train_cfg, mesh, apply_fn=None, params=adapters,
                          loss_fn=loss_fn)

    if script_args.packing:
        def batches():
            gen = constant_length_batches(
                train, tok, script_args.seq_length, infinite=True,
                chars_per_token=ratio,
            )
            gb = trainer.global_train_batch()
            while True:
                yield np.stack([next(gen) for _ in range(gb)])

        train_iter = batches()
        eval_blocks = None
        if valid:
            rows = list(constant_length_batches(
                valid, tok, script_args.seq_length, infinite=False,
                chars_per_token=ratio,
            ))
            if rows:
                eval_blocks = np.stack(rows)
    else:
        # non-packed: one example per row, padded + loss-masked, optionally
        # length-grouped (the reference base trainer's alternative to
        # ConstantLengthDataset, sft_llama2.py:53-54)
        from distributed_lion_tpu.data.sft import padded_batch_iterator, padded_examples

        tr_tokens, tr_mask = padded_examples(
            train, tok, script_args.seq_length,
            group_by_length=script_args.group_by_length,
        )
        train_iter = padded_batch_iterator(
            tr_tokens, tr_mask, trainer.global_train_batch(),
            seed=train_cfg.seed,
            length_grouped=script_args.group_by_length,
        )
        eval_blocks = None
        if valid:
            ev_tokens, ev_mask = padded_examples(valid, tok, script_args.seq_length)
            eval_blocks = {"tokens": ev_tokens, "mask": ev_mask}

    try:
        trainer.train(train_iter, eval_blocks=eval_blocks)
        if trainer.preempted:
            print("[run_sft] preempted: "
                  + ("checkpoint durable, " if trainer.checkpointer
                     else "NO checkpointer (no --output_dir) — nothing "
                          "saved, ")
                  + "exiting cleanly")
            return
        if eval_blocks is not None:
            trainer.evaluate(eval_blocks)
        if trainer.checkpointer:
            trainer.save()
        if script_args.adapter_output:
            from distributed_lion_tpu.models.hf_export import lora_to_peft

            lora_to_peft(jax.device_get(trainer.params), model_cfg, lora_cfg,
                         script_args.adapter_output,
                         base_model_name=script_args.model_path or "")
            print(f"[run_sft] PEFT adapter saved to {script_args.adapter_output}")
        # merge_and_unload parity (sft_llama2.py:183-199)
        if script_args.merged_output:
            from distributed_lion_tpu.ops.quant import dequantize_tree

            merged = dequantize_tree(merge_lora(base_params, trainer.params, lora_cfg))
            if script_args.merged_output.endswith(".npz"):
                save_pytree(script_args.merged_output, merged)
            else:
                # HF save_pretrained layout — loadable by
                # LlamaForCausalLM.from_pretrained, the format the
                # reference's merge flow emits (sft_llama2.py:196-199)
                from distributed_lion_tpu.models.hf_export import (
                    copy_tokenizer_files, llama_to_hf)

                llama_to_hf(jax.device_get(merged), model_cfg,
                            script_args.merged_output)
                copy_tokenizer_files(script_args.tokenizer_name
                                     or script_args.model_path,
                                     script_args.merged_output)
            print(f"[run_sft] merged model saved to {script_args.merged_output}")
    finally:
        trainer.close()


def _train_cfg_cls():
    from distributed_lion_tpu.train.loop import TrainConfig

    return TrainConfig


if __name__ == "__main__":
    main()
