"""Kernel autotuner CLI: measure tile candidates, commit winners to the cache.

    python -m distributed_lion_tpu.cli.run_tune --preset flagship
    python -m distributed_lion_tpu.cli.run_tune --preset smoke --in-process
    python -m distributed_lion_tpu.cli.run_tune --knobs lion_row_block

Each candidate runs as a CHILD process under a hard per-candidate timeout
(``ops/autotune.run_trial_child``) covering compile AND run — round 3 lost
>14 min of a TPU window to one hand-picked flash tile (1024x1024) hanging
remote compile; under the tuner the worst a pathological tile can cost is
``--timeout_s``. Winners (minimum ms, ties to the smallest tile —
``autotune.select_winner``) are merged into the device-keyed tuning cache
(``scripts/tuning_cache.json`` by default, ``$DLT_TUNE_CACHE`` override),
which ``ops/attention`` ``auto`` dispatch, the Trainer's ``kernel='auto'``
path and ``resolve_auto_comm``'s ``vote_buckets`` sentinel then consult.

``--in-process`` skips the child processes (no hang protection — a wedged
compile wedges the tuner) and exists for CPU CI, where the interpret/xla
fallbacks cannot hang and child-spawn latency would dominate. The knob set
degrades honestly off-TPU: flash/splash trials report
``unsupported`` (there is no tile to tune in the xla fallback) while
lion_row_block / vocab_chunks / vote_buckets still run, so a CPU pass
produces a valid — cpu-keyed, therefore TPU-inert — cache artifact that
exercises the full search/commit path end to end.

Prints one JSON summary line (runbook-parseable):
``{"tuned": {...}, "skipped": {...}, "backend": ..., "device_kind": ...,
"cache": path}``. Exit 0 when every requested knob either tuned or was
skipped-with-reason; exit 1 when a supported knob's candidates ALL failed
(that is a bug or a sick backend, not a tuning outcome).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from distributed_lion_tpu.ops import autotune

# Shape presets. 'flagship' is the ROADMAP-1 anchor workload — GPT-2 124M
# at the promoted bench config (microbatch 4 × T=1024, head_dim 64, bf16
# compute, 50257-vocab chunked CE, the 124,439,808-coordinate ballot).
# 'smoke' is the CPU CI scale: same structure, minutes not hours. The odd
# smoke coordinate counts are deliberate — they can never collide with a
# shape some test resolves through the committed cache.
PRESETS = {
    "flagship": {
        "attn": {"b": 4, "h": 12, "t": 1024, "d": 64, "dtype": "bfloat16"},
        # the flagship bench config runs bf16 momenta (mom_dtype bfloat16)
        "lion": {"n": 124_439_808, "dtype": "bfloat16"},
        "xent": {"n": 4096, "d": 768, "v": 50257, "dtype": "bfloat16"},
    },
    "smoke": {
        "attn": {"b": 1, "h": 2, "t": 128, "d": 64, "dtype": "float32"},
        "lion": {"n": 1_048_581, "dtype": "float32"},
        "xent": {"n": 256, "d": 64, "v": 509, "dtype": "float32"},
    },
}
# the knob whitelist is the schema's (ops/autotune.KNOBS) — one authority,
# so the CLI's validation and the cache validator cannot drift
DEFAULT_KNOBS = autotune.KNOBS


def _knob_info(knob: str, preset: dict) -> dict:
    if knob in ("flash_tiles", "splash_tiles"):
        return dict(preset["attn"])
    if knob in ("lion_row_block", "vote_buckets"):
        return dict(preset["lion"])
    if knob == "vocab_chunks":
        return dict(preset["xent"])
    raise ValueError(f"unknown knob {knob!r}")


def _shape_key(knob: str, info: dict) -> str:
    if knob in ("flash_tiles", "splash_tiles"):
        return autotune.attn_shape_key(info["t"], info["d"])
    if knob in ("lion_row_block", "vote_buckets"):
        return f"N{info['n']}"
    return f"N{info['n']}xV{info['v']}"


def _key_dtype(knob: str, info: dict) -> str:
    """The dtype component of the cache key: the dtype the knob's tiling
    actually varies over — qkv dtype for attention tiles, momentum dtype
    for the lion kernels, hidden dtype for chunked CE, and the constant
    int8 wire payload for vote_buckets (its resolver,
    train.loop.resolve_auto_comm, has no float dtype in scope)."""
    if knob == "vote_buckets":
        return "int8"
    return str(info.get("dtype", "float32"))


def _measure(knob: str, candidates: list, info: dict, args,
             base: dict | None = None, journal=None) -> list:
    """Candidate-ordered result rows for one knob; every row is printed as
    it lands so a killed tuner still leaves a legible trail."""
    results = []
    for cand in candidates:
        payload = {"knob": knob, "candidate": cand, "info": info,
                   "iters": args.iters}
        if base:
            payload["info"] = {**info, "base": base}
        if args.test_sleep_s:  # timeout-guard test hook (see autotune)
            payload["_test_sleep_s"] = args.test_sleep_s
        if args.in_process:
            t0 = time.monotonic()
            r = autotune.execute_trial(payload)
            # same span writer as the child path: one record shape, same
            # per-trial flush, same never-break-the-search guard
            autotune.journal_trial(journal, knob, cand, r, t0)
        else:
            r = autotune.run_trial_child(payload, args.timeout_s,
                                         journal=journal)
        row = {"knob": knob, "candidate": cand,
               "ms": r.get("ms"), "error": r.get("error")}
        print(json.dumps({k: v for k, v in row.items() if v is not None},
                         allow_nan=False), file=sys.stderr, flush=True)
        results.append(row)
        if r.get("error", "").startswith("unsupported"):
            break  # one unsupported row describes the whole knob
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--knobs", default=",".join(DEFAULT_KNOBS),
                    help="comma-separated subset of: " + ", ".join(DEFAULT_KNOBS))
    ap.add_argument("--preset", choices=sorted(PRESETS), default="flagship")
    ap.add_argument("--cache", default=None,
                    help="cache path (default scripts/tuning_cache.json "
                         "or $DLT_TUNE_CACHE)")
    ap.add_argument("--timeout_s", type=float, default=600.0,
                    help="per-candidate compile+run budget; on expiry the "
                         "candidate's process group is SIGKILLed")
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--in-process", action="store_true",
                    help="run trials in this process (NO hang protection; "
                         "CPU CI only)")
    ap.add_argument("--skip_cached", action="store_true",
                    help="skip knobs that already hold a cache entry for "
                         "this device/shape/dtype — the runbook's re-fire "
                         "resume: a dropped window re-tunes only the "
                         "missing knobs")
    ap.add_argument("--journal_dir", default=None,
                    help="record a run journal (train/journal.py) of the "
                         "tuning session — one autotune/trial span per "
                         "candidate with knob, candidate, ms/error and "
                         "child wall time; analyze with cli/run_analyze")
    ap.add_argument("--test_sleep_s", type=float, default=0.0,
                    help=argparse.SUPPRESS)  # timeout-guard test hook
    ap.add_argument("--trial", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.trial is not None:
        # child mode: one guarded candidate — print the result JSON and out
        print(json.dumps(autotune.execute_trial(json.loads(args.trial)),
                         allow_nan=False), flush=True)
        return 0

    autotune.install_trial_teardown()
    # Backend discovery WITHOUT initializing jax in this process when
    # trials run as children: libtpu is single-client, so a parent that
    # opens the chip starves every trial child of it (bench.py's
    # orchestrator "never imports jax itself" for exactly this reason —
    # the CPU smoke path can't catch the mistake because CPUs have no
    # device lock). The probe is itself a guarded child; --in-process mode
    # runs trials here anyway, so there the direct import is correct.
    if args.in_process:
        import jax

        backend = jax.default_backend()
        device_kind = autotune.current_device_kind()
    else:
        probe = autotune.run_trial_child({"knob": "_probe"}, args.timeout_s)
        if "backend" not in probe:
            print(f"backend probe failed: {probe.get('error')}",
                  file=sys.stderr)
            return 1
        backend, device_kind = probe["backend"], probe["device_kind"]
    preset = PRESETS[args.preset]
    knobs = [k.strip() for k in args.knobs.split(",") if k.strip()]
    unknown = [k for k in knobs if k not in DEFAULT_KNOBS]
    if unknown:
        ap.error(f"unknown knob(s) {unknown}; pick from {DEFAULT_KNOBS}")

    jr = None
    if args.journal_dir:
        from distributed_lion_tpu.train.journal import Journal

        jr = Journal(args.journal_dir)
        jr.event("tune_start", preset=args.preset, backend=backend,
                 device_kind=device_kind)
    entries = dict(autotune.load_cache(args.cache))
    tuned: dict = {}
    skipped: dict = {}
    failed: dict = {}
    cache_file = None
    cached: dict = {}
    try:
        for knob in knobs:
            info = _knob_info(knob, preset)
            key = autotune.cache_key(device_kind, knob,
                                     _shape_key(knob, info),
                                     _key_dtype(knob, info))
            if args.skip_cached and key in entries:
                cached[knob] = key
                continue
            results = _measure(knob, autotune.tile_candidates(knob, info),
                               info, args, journal=jr)
            if results and str(results[-1].get("error", "")).startswith(
                    "unsupported"):
                skipped[knob] = results[-1]["error"]
                continue
            win = autotune.select_winner(results)
            if win is None:
                failed[knob] = [r.get("error") for r in results][:3]
                continue
            value = dict(win["candidate"])
            if knob == "flash_tiles":
                # phase 2: backward tiles, with the winning forward tiles
                # pinned (the bwd passes are ~2× the fwd FLOPs with
                # different operand shapes — VERDICT's named lever).
                # Deterministic: the phase-2 grid and tie-break are as
                # fixed as phase 1's.
                bwd = _measure(
                    "flash_tiles_bwd",
                    autotune.tile_candidates("flash_tiles_bwd", info),
                    info, args, base=value, journal=jr)
                bwin = autotune.select_winner(bwd)
                if bwin is not None:
                    value.update(bwin["candidate"])
                    win["ms"] = bwin["ms"]
            entries[key] = {
                "value": value,
                "ms": round(float(win["ms"]), 4),
                "backend": backend,
                "candidates": len(results),
                "measured": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime()),
            }
            tuned[knob] = {"key": key, "value": value,
                           "ms": entries[key]["ms"]}
            # commit after EVERY knob (atomic tmp+rename): a dropped TPU
            # window keeps the knobs it finished — the same at-most-one-
            # interval loss discipline as the parity legs' checkpoints
            cache_file = autotune.save_cache(entries, args.cache)
    finally:
        # flush/close even when a knob raises: a crashed or killed tuner
        # must still leave a legible journal (journal_trial flushed after
        # every candidate; this seals the file)
        if jr is not None:
            jr.event("tune_end", tuned=len(tuned), skipped=len(skipped),
                     failed=len(failed))
            jr.close()
    print(json.dumps({
        "tuned": tuned, "cached": cached, "skipped": skipped,
        "failed": failed, "backend": backend, "device_kind": device_kind,
        "cache": cache_file,
    }, allow_nan=False), flush=True)
    # exit contract: a knob whose trials ALL errored (not 'unsupported')
    # signals a sick backend or a tuner bug — loud, so the runbook stage
    # logs it red instead of quietly committing a partial cache
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
