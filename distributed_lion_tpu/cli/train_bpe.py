"""Train a GPT-2-format byte-level BPE tokenizer on local text.

Zero-egress stand-in for downloading GPT-2's tokenizer from HF hub
(/root/reference/run_clm.py:398-423): learns vocab.json + merges.txt in the
exact published format (loadable by this framework via
``--tokenizer_name bpe:<dir>`` AND by ``transformers.GPT2Tokenizer``), so a
corpus-specific vocabulary — or, when the real GPT-2 files are available
locally, the true 50257-token vocabulary — drives the ``text:`` data path.

    python -m distributed_lion_tpu.cli.train_bpe \
        --text 'corpus/*.txt' --output_dir tok/ --vocab_size 8192
"""

from __future__ import annotations

import dataclasses
import glob


@dataclasses.dataclass
class BPEArguments:
    text: str = ""            # glob of local text files
    output_dir: str = "bpe_tok"
    vocab_size: int = 8192
    max_chars: int = 50_000_000  # training-corpus cap (BPE training is
    # quadratic-ish in merges x corpus; cap keeps it tractable)


def main(argv=None):
    from distributed_lion_tpu.data.bpe import train_bpe
    from distributed_lion_tpu.utils.argparsing import parse_dataclasses

    (args,) = parse_dataclasses((BPEArguments,), argv)
    paths = sorted(glob.glob(args.text))
    if not paths:
        raise FileNotFoundError(f"no files match {args.text!r}")

    def texts():
        budget = args.max_chars
        for p in paths:
            with open(p, encoding="utf-8", errors="replace") as f:
                chunk = f.read(budget)
            yield chunk
            budget -= len(chunk)
            if budget <= 0:
                return

    tok = train_bpe(texts(), args.vocab_size)
    tok.save(args.output_dir)
    print(f"[train_bpe] {tok.vocab_size}-token vocabulary "
          f"({len(tok.ranks)} merges) saved to {args.output_dir}")


if __name__ == "__main__":
    main()
