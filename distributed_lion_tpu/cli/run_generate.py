"""Text-generation entry point: load an exported model, decode with KV cache.

Net-new vs the reference (it has no inference path). Completes the train →
export → use cycle: ``run_clm``/``run_sft`` export ``model.npz`` via
utils.serialization; this CLI loads it and generates.

    python -m distributed_lion_tpu.cli.run_generate \
        --model_path ./out/model.npz --model_family gpt2 --model_name tiny \
        --prompt "Question: " --max_new_tokens 64 --temperature 0.8 --top_k 40

With no --model_path, random-init weights are used (smoke mode).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional


@dataclasses.dataclass
class GenerateArguments:
    model_path: Optional[str] = None  # .npz from utils.serialization, or an
    # HF save_pretrained directory (hf_export/--merged_output output, family
    # auto-detected); unset → random init (smoke mode)
    model_family: str = "gpt2"  # gpt2 | llama
    model_name: str = "tiny"    # gpt2: gpt2_124m | tiny; llama: llama2_7b | llama3_8b | tiny
    tokenizer_name: Optional[str] = None  # HF cache name; byte tokenizer otherwise
    prompt: List[str] = dataclasses.field(default_factory=list)
    # one or more prompts (--prompt "a" "b" "c"); several prompts batch into
    # ONE left-padded generate call with per-row position offsets — each
    # row attends/positions exactly as its solo run would (greedy outputs
    # are identical to solo runs; see main() on sampling). With neither
    # --prompt nor --prompt_file, "Hello" is the smoke default
    prompt_file: Optional[str] = None  # one prompt per line; appended to
    # --prompt (blank lines skipped)
    max_new_tokens: int = 64
    temperature: float = 0.8
    top_k: Optional[int] = 40
    top_p: Optional[float] = None  # nucleus sampling mass (e.g. 0.95)
    seed: int = 0
    vocab_size: Optional[int] = None
    moe_experts: int = 0  # > 0: the checkpoint is Switch-MoE (gpt2 only;
    # must match the training --moe_experts/--moe_every — model.npz holds
    # no config stamp, and the serve engine's expert-parallel and
    # capacity-aware paths key off the declared config). HF-dir
    # checkpoints ignore it (no MoE export format).
    moe_every: int = 2


def _is_hf_dir(path: Optional[str]) -> bool:
    import os

    # A training --output_dir holds model.npz but no config.json; only route
    # directories that look like save_pretrained output to the HF importer.
    return bool(path) and os.path.isdir(path) and os.path.isfile(
        os.path.join(path, "config.json"))


def build(args: GenerateArguments):
    import os

    import jax

    from distributed_lion_tpu.data.tokenizer import load_tokenizer
    from distributed_lion_tpu.utils.serialization import load_pytree

    tok = load_tokenizer(args.tokenizer_name)
    vocab = args.vocab_size or tok.vocab_size

    if (args.model_path and os.path.isdir(args.model_path)
            and not _is_hf_dir(args.model_path)):
        # a training --output_dir: the weights live at <dir>/model.npz
        npz = os.path.join(args.model_path, "model.npz")
        if os.path.isfile(npz):
            args.model_path = npz
        else:
            raise FileNotFoundError(
                f"{args.model_path!r} is a directory with neither config.json "
                "(HF checkpoint) nor model.npz (training output)"
            )

    hf_params = hf_cfg = None
    if _is_hf_dir(args.model_path):
        # an HF save_pretrained directory (e.g. run_clm --hf_export or
        # run_sft --merged_output <dir>): import it, family auto-detected
        from distributed_lion_tpu.models import hf_import

        family = hf_import.detect_family(args.model_path)
        if family != args.model_family:
            print(f"[run_generate] --model_family {args.model_family} -> "
                  f"{family} (detected from checkpoint)")
            args.model_family = family
        loader = (hf_import.gpt2_from_hf if family == "gpt2"
                  else hf_import.llama_from_hf)
        hf_params, hf_cfg = loader(args.model_path)

    if args.model_family == "gpt2":
        from distributed_lion_tpu.models.gpt2 import (
            GPT2Config, gpt2_decode, gpt2_init, gpt2_init_cache,
        )

        moe_kw = ({"moe_experts": args.moe_experts,
                   "moe_every": args.moe_every}
                  if args.moe_experts > 0 else {})
        cfg = hf_cfg or (
            GPT2Config.tiny if args.model_name == "tiny" else GPT2Config.gpt2_124m
        )(vocab_size=vocab, **moe_kw)
        params = (hf_params if hf_params is not None
                  else load_pytree(args.model_path) if args.model_path
                  else gpt2_init(jax.random.key(args.seed), cfg))
        decode = partial(
            lambda c, p, t, k, pos, off=None: gpt2_decode(p, t, c, k, pos, off),
            cfg)
        init_cache = partial(gpt2_init_cache, cfg)
    elif args.model_family == "llama":
        from distributed_lion_tpu.models.llama import (
            LlamaConfig, llama_decode, llama_init, llama_init_cache,
        )

        cfg = hf_cfg or LlamaConfig.named(args.model_name, vocab_size=vocab)
        params = (hf_params if hf_params is not None
                  else load_pytree(args.model_path) if args.model_path
                  else llama_init(jax.random.key(args.seed), cfg))
        decode = partial(
            lambda c, p, t, k, pos, off=None: llama_decode(p, t, c, k, pos, off),
            cfg)
        init_cache = partial(llama_init_cache, cfg)
    else:
        raise ValueError(f"unknown model family {args.model_family!r}")
    return tok, cfg, params, decode, init_cache


def main(argv=None):
    import os

    import jax

    from distributed_lion_tpu.parallel.mesh import force_cpu_platform

    force_cpu_platform()
    import jax.numpy as jnp
    import numpy as np

    from distributed_lion_tpu.models.generate import generate
    from distributed_lion_tpu.utils.argparsing import parse_dataclasses

    (args,) = parse_dataclasses((GenerateArguments,), argv)
    tok, cfg, params, decode, init_cache = build(args)
    prompts = list(args.prompt)
    if args.prompt_file:
        with open(args.prompt_file) as f:
            prompts += [ln.rstrip("\n") for ln in f if ln.strip()]
        if not prompts:
            raise ValueError(
                f"no prompts: --prompt_file {args.prompt_file!r} holds no "
                "non-blank lines and no --prompt was given")
    elif not prompts:
        prompts = ["Hello"]  # the historical smoke default
    # NOTE: at temperature > 0 the batched draws share one PRNG stream
    # over the [B, V] batch, so SAMPLED continuations differ from solo
    # invocations (greedy rows are identical to solo runs — pinned by
    # test); per-request streams live in the serving engine (run_serve)
    ids = [tok.encode(p, add_bos=False) or [0] for p in prompts]
    T = max(len(i) for i in ids)
    # LEFT-pad to the longest prompt: every row's last prompt token sits at
    # slot T-1 (so one shared sampling position), and the pad widths flow
    # to the model as per-row position offsets + attention masks — each
    # row attends and positions exactly as its solo run would
    batch = np.zeros((len(ids), T), np.int32)
    for r, seq in enumerate(ids):
        batch[r, T - len(seq):] = seq
    lens = jnp.asarray([len(seq) for seq in ids], jnp.int32)
    out = generate(
        decode, init_cache, params, jnp.asarray(batch), args.max_new_tokens,
        key=jax.random.key(args.seed), temperature=args.temperature,
        top_k=args.top_k, top_p=args.top_p,
        eos_id=getattr(tok, "eos_id", None),
        prompt_lens=None if len(ids) == 1 else lens,
    )
    texts = [tok.decode([int(t) for t in row]) for row in out]
    for p, t in zip(prompts, texts):
        print(p + t)
    return texts[0] if len(texts) == 1 else texts


if __name__ == "__main__":
    main()
