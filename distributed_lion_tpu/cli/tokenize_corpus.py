"""Tokenize a local text corpus into memory-mapped ``.bin`` token shards.

The reference prepares openwebtext with ``datasets.map(tokenize,
num_proc=N)`` + ``group_texts`` and caches the result as Arrow files
(/root/reference/run_clm.py:463-544). The zero-egress, framework-native
equivalent: parallel worker processes run the byte-level BPE (with the C++
merge core, native/bpe_core.cc), docs are ``<|endoftext|>``-joined into one
flat token stream, and the stream is written as fixed-size ``.bin`` shards
(uint16 when the vocab fits, else uint32) plus a ``meta.json`` — exactly
what the C++ mmap data loader (``--native_loader``) and
``data.sources.TokenDataset.from_bin`` consume.

    python -m distributed_lion_tpu.cli.tokenize_corpus \
        --text 'corpus/**/*.txt' --tokenizer bpe:tok/ --output_dir data/owt

Documents are processed in deterministic input order regardless of worker
count, so a corpus tokenizes to byte-identical shards at any ``num_proc``.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import pathlib
from typing import Iterator, List

import numpy as np


@dataclasses.dataclass
class TokenizeArguments:
    text: str = ""             # glob of local .txt / .jsonl files
    jsonl_field: str = "text"  # field holding the document in .jsonl inputs
    tokenizer: str = ""        # bpe:<dir>, a vocab/merges dir, or '' (byte)
    output_dir: str = "tokenized"
    shard_tokens: int = 64_000_000  # tokens per .bin shard
    num_proc: int = 0          # worker processes; 0 = cpu count (cap 16)
    doc_sep_eos: bool = True   # append <|endoftext|> after every document


def _iter_docs(paths: List[str], jsonl_field: str) -> Iterator[str]:
    """Yield documents in deterministic path-then-line order."""
    for p in paths:
        if p.endswith(".jsonl"):
            with open(p, encoding="utf-8", errors="replace") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        obj = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    doc = obj.get(jsonl_field) if isinstance(obj, dict) else None
                    if isinstance(doc, str) and doc:
                        yield doc
        else:
            text = pathlib.Path(p).read_text(encoding="utf-8", errors="replace")
            if text:
                yield text


_WORKER_TOK = None


def _worker_init(tokenizer_name: str) -> None:
    global _WORKER_TOK
    from distributed_lion_tpu.data.tokenizer import load_tokenizer

    _WORKER_TOK = load_tokenizer(tokenizer_name or None)


def _worker_encode(args: tuple) -> bytes:
    """Encode one document; returns raw little-endian uint32 id bytes
    (cheap to pickle back to the writer process)."""
    doc, add_eos = args
    ids = _WORKER_TOK.encode(doc, add_eos=add_eos)
    return np.asarray(ids, np.uint32).tobytes()


class _ShardWriter:
    """Accumulate a flat token stream into fixed-size .bin shards."""

    def __init__(self, out_dir: str, shard_tokens: int, dtype):
        self.dir = pathlib.Path(out_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.shard_tokens = shard_tokens
        self.dtype = dtype
        self.paths: List[str] = []
        self.total = 0
        self._buf: List[np.ndarray] = []
        self._buffered = 0

    def add(self, ids: np.ndarray) -> None:
        self._buf.append(ids)
        self._buffered += ids.size
        self.total += ids.size
        while self._buffered >= self.shard_tokens:
            flat = np.concatenate(self._buf)
            self._write(flat[: self.shard_tokens])
            rest = flat[self.shard_tokens:]
            self._buf = [rest] if rest.size else []
            self._buffered = rest.size

    def _write(self, chunk: np.ndarray) -> None:
        path = self.dir / f"shard_{len(self.paths):05d}.bin"
        chunk.astype(self.dtype).tofile(path)
        self.paths.append(path.name)
        print(f"[tokenize_corpus] wrote {path} ({chunk.size:,} tokens)")

    def finish(self) -> None:
        if self._buffered:
            self._write(np.concatenate(self._buf))
            self._buf, self._buffered = [], 0


def main(argv=None) -> None:
    from distributed_lion_tpu.data.tokenizer import load_tokenizer
    from distributed_lion_tpu.utils.argparsing import parse_dataclasses

    (args,) = parse_dataclasses((TokenizeArguments,), argv)
    paths = sorted(glob.glob(args.text, recursive=True))
    if not paths:
        raise FileNotFoundError(f"no files match {args.text!r}")

    tok = load_tokenizer(args.tokenizer or None)
    dtype = np.uint16 if tok.vocab_size <= 65536 else np.uint32
    writer = _ShardWriter(args.output_dir, args.shard_tokens, dtype)

    num_proc = args.num_proc or min(os.cpu_count() or 1, 16)
    docs = _iter_docs(paths, args.jsonl_field)
    n_docs = 0
    if num_proc > 1:
        import multiprocessing as mp

        ctx = mp.get_context("spawn")  # fork-safety: jax/XLA may be loaded
        with ctx.Pool(num_proc, initializer=_worker_init,
                      initargs=(args.tokenizer,)) as pool:
            jobs = ((d, args.doc_sep_eos) for d in docs)
            # imap (ordered) keeps output deterministic at any num_proc
            for blob in pool.imap(_worker_encode, jobs, chunksize=8):
                writer.add(np.frombuffer(blob, np.uint32))
                n_docs += 1
    else:
        for doc in docs:
            ids = tok.encode(doc, add_eos=args.doc_sep_eos)
            writer.add(np.asarray(ids, np.uint32))
            n_docs += 1
    writer.finish()

    meta = {
        "dtype": np.dtype(dtype).name,
        "vocab_size": int(tok.vocab_size),
        "tokenizer": args.tokenizer,
        "eos_id": int(getattr(tok, "eos_id", 0)),
        "n_tokens": writer.total,
        "n_docs": n_docs,
        "shards": writer.paths,
    }
    with open(os.path.join(args.output_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1, allow_nan=False)
    print(f"[tokenize_corpus] {n_docs} docs -> {writer.total:,} tokens in "
          f"{len(writer.paths)} shard(s) ({np.dtype(dtype).name}) at "
          f"{args.output_dir}")


if __name__ == "__main__":
    main()
