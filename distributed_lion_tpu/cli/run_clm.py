"""Causal-LM pretraining entry point — the reference's ``run_clm.py``
workload (GPT-2 on openwebtext, /root/reference/run_clm.py, README.md:18-38)
rebuilt TPU-native.

Canonical launch (maps the reference's ``torchrun --nproc_per_node 4
run_clm.py --lion --async_grad ...``, README.md:19-38):

    python -m distributed_lion_tpu.cli.run_clm \
        --lion --async_grad --model_name gpt2_124m \
        --dataset synthetic --per_device_train_batch_size 20 \
        --gradient_accumulation_steps 8 --learning_rate 1e-4 \
        --weight_decay 0.1 --warmup_steps 2000 --max_steps 100000 \
        --block_size 1024 --output_dir ./out

There is no torchrun: the device mesh comes from ``jax.devices()`` (all
local chips → the ``data`` axis) or multi-host ``jax.distributed``. Data
sources (zero-egress substitutes for HF-hub streaming): ``synthetic``,
``text:<glob>`` (local files via the byte/HF-cache tokenizer), or
``bin:<path>`` (pre-tokenized uint16 memmap, e.g. an openwebtext dump).
Set env ``DLION_PLATFORM=cpu8`` to force an 8-virtual-device CPU mesh.

Observability flags (train/telemetry.py; README "Observability"):
``--telemetry`` arms vote-health telemetry (on-device margin histogram /
flip rate / disagreement, measured-vs-analytic wire drift, multi-host
heartbeat), ``--nan_sentinel`` the per-step isfinite watch with crash
bundles under ``output_dir/crash/``, ``--trace_on_anomaly`` a profiler
window at the tripping step.
"""

from __future__ import annotations

import dataclasses
import glob
import os
from typing import Optional


@dataclasses.dataclass
class ModelArguments:
    """run_clm.py ModelArguments (:89-166) — the subset that configures a
    from-scratch model rather than an HF hub download."""

    model_family: str = "gpt2"  # gpt2 | llama — the reference's run_clm is
    # architecture-agnostic (AutoModelForCausalLM, run_clm.py:425-444);
    # llama composes with dp x tp x sp (pipe/expert/MoE are GPT-2-only)
    model_name: str = "gpt2_124m"  # gpt2: gpt2_124m | gpt2_small | tiny;
    # llama: llama2_7b | llama3_8b | tiny
    model_path: Optional[str] = None  # local HF checkpoint (save_pretrained
    # dir / .safetensors / .bin / .npz) → finetune from pretrained weights,
    # the reference's from_pretrained path (run_clm.py:425-444). Overrides
    # model_name's architecture with the checkpoint's.
    hf_export: Optional[str] = None  # also write the final model as an HF
    # save_pretrained directory (models/hf_export) — the reference's
    # save_model output format (run_clm.py:611-622)
    vocab_size: Optional[int] = None  # default: tokenizer/model default
    n_ctx: Optional[int] = None
    dropout: Optional[float] = None  # None = family default: 0.1 for GPT-2
    # (the reference trains from the HF GPT-2 config, whose every pdrop knob
    # defaults to 0.1 — /root/reference/run_clm.py:425-444), 0.0 for Llama
    # (no dropout), under --pipeline_parallel (unsupported there; explicit
    # --dropout with pp still fails loudly in validate_pipeline), and under
    # --seq_parallel (attention-prob dropout is skipped there; explicit
    # --dropout opts into the partial semantics — see resolve_dropout)
    seq_impl: str = "ring"  # sequence-parallel attention under
    # --seq_parallel: 'ring' (kv rotation) | 'ulysses' (all_to_all to head
    # sharding; needs n_head % seq_parallel == 0)
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    remat: bool = True  # per-block activation remat (off = faster when HBM allows)
    remat_policy: str = "full"  # 'full' (recompute the whole block) |
    # 'dots' (keep matmul outputs, recompute elementwise — cheaper backward
    # at slightly more HBM; models/gpt2._remat_policy)
    moe_experts: int = 0  # > 0: Switch-MoE FFN every moe_every-th block
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    vocab_pad_multiple: int = 0  # gpt2 only: round the embedding-table rows
    # up to this multiple (e.g. 1024 → 50257 becomes 51200) so the tied
    # head / chunked-CE slices are MXU-tile-aligned and --tp_vocab shards
    # evenly; loss/generation semantics are exact (models/gpt2)


def resolve_dropout(dropout: Optional[float], family: str, pp: int,
                    sp: int = 1) -> float:
    """Family-default dropout (None = unset): 0.1 for GPT-2 pretraining —
    the reference instantiates the HF GPT-2 config, whose every pdrop knob
    defaults to 0.1 (/root/reference/run_clm.py:425-444). 0.0 for Llama
    (no dropout), under pipeline parallelism (unsupported there; an
    EXPLICIT nonzero value still fails loudly in validate_pipeline / the
    Llama guard rather than being silently zeroed here), and under
    sequence parallelism — sp skips attention-prob dropout (the scores
    never exist in one place, models/gpt2), so 0.1 would be a DIFFERENT
    regularizer than the reference default this function promises; an
    explicit --dropout under sp opts into that partial semantics (the
    trainer prints the semantics warning)."""
    if dropout is not None:
        return dropout
    return 0.1 if family == "gpt2" and pp <= 1 and sp <= 1 else 0.0


@dataclasses.dataclass
class DataArguments:
    """run_clm.py DataTrainingArguments (:169-244), zero-egress edition."""

    dataset: str = "synthetic"  # synthetic | text:<glob> | bin:<path>
    tokenizer_name: Optional[str] = None
    validation_split_percentage: int = 5  # run_clm.py:181-184
    max_train_samples: Optional[int] = None  # debug truncation (:186-203)
    max_eval_samples: Optional[int] = None
    synthetic_blocks: int = 4096
    native_loader: bool = True  # C++ mmap+prefetch loader for bin: datasets
    bin_dtype: str = "uint16"  # token width of bin: shards (uint16 | uint32)


def build_mesh(tensor_parallel: int = 1, seq_parallel: int = 1,
               pipeline_parallel: int = 1, expert_parallel: int = 1):
    from distributed_lion_tpu.parallel.mesh import (
        force_cpu_platform,
        make_mesh,
        multihost_initialize,
    )

    force_cpu_platform()
    # distributed init FIRST: the cache gate probes jax.default_backend(),
    # which initializes XLA backends — with backends up,
    # jax.distributed.initialize() raises and multihost_initialize
    # re-raises it loudly (parallel/mesh.py), failing the launch instead of
    # training N silently-disconnected replicas. The order is correctness,
    # not optimization.
    multihost_initialize()
    enable_compilation_cache()
    return make_mesh(tensor=tensor_parallel, seq=seq_parallel,
                     pipe=pipeline_parallel, expert=expert_parallel)


def _host_signature() -> str:
    """Short hash of the host's CPU identity. The cache directory is scoped
    by it because $HOME persists while sessions migrate across hosts —
    XLA:CPU AOT executables compiled on one machine SIGILL/abort when
    loaded on another with different CPU features (observed in practice:
    a cache populated on a prior host fatally aborted later CLI runs)."""
    import hashlib
    import platform

    ident = platform.machine()
    seen = set()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                key = line.split(":", 1)[0].strip()
                # model name AND flags: same model can expose different
                # feature sets under different hypervisors/microcode
                if key in ("flags", "model name", "Features") and key not in seen:
                    seen.add(key)
                    ident += line
    except OSError:
        pass
    return hashlib.sha1(ident.encode()).hexdigest()[:10]


def enable_compilation_cache() -> None:
    """Persistent XLA compilation cache (~20-40s per TPU compile amortized
    across runs). Opt-out with DLION_COMPILE_CACHE=0; directory override via
    DLION_COMPILE_CACHE_DIR.

    TPU backend only. XLA:CPU AOT cache entries compiled on one host
    fatally abort the process when loaded on a host with different CPU
    features, and the per-CPU-signature directory suffix cannot fully
    discriminate hosts (XLA feature-detects via cpuid; /proc/cpuinfo can be
    virtualized identically across different hardware — an abort was still
    observed under the signature scheme). CPU compiles are fast enough that
    caching them buys little, so the cache is simply not enabled off-TPU;
    the signature suffix is kept as defense in depth for session migration
    between TPU hosts. Pin DLION_COMPILE_CACHE_DIR to share a cache across
    known-identical hosts."""
    import jax

    if os.environ.get("DLION_COMPILE_CACHE", "1") == "0":
        return
    try:
        backend = jax.default_backend()
    except RuntimeError:
        return
    if backend != "tpu":
        return
    cache_dir = os.environ.get(
        "DLION_COMPILE_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache",
                     f"dlion_xla_{_host_signature()}"),
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # older jax without the knob: run uncached
        print(f"[run_clm] compilation cache unavailable: {e}")


VOCAB_PROBE_TOKENS = 4_000_000  # sample budget for the token-id range check


def _check_vocab(max_token_id: int, vocab_size: int) -> None:
    # token ids must fit the model's embedding table — XLA gather would
    # silently clamp out-of-range ids into wrong-but-running training.
    if max_token_id >= vocab_size:
        raise ValueError(
            f"dataset contains token id {max_token_id} >= model vocab_size "
            f"{vocab_size}; set --vocab_size (or use a matching tokenizer)"
        )


def _bin_paths(spec: str) -> list:
    paths = sorted(glob.glob(spec[len("bin:"):]))
    if not paths:
        raise FileNotFoundError(f"no files match {spec!r}")
    return paths


def load_blocks(data_args: DataArguments, block_size: int, vocab_size: int):
    import numpy as np

    from distributed_lion_tpu.data.sources import (
        TokenDataset,
        synthetic_lm_dataset,
        tokens_from_text_files,
    )

    if data_args.dataset == "synthetic":
        blocks = synthetic_lm_dataset(data_args.synthetic_blocks, block_size, vocab_size)
    elif data_args.dataset.startswith("text:"):
        paths = sorted(glob.glob(data_args.dataset[len("text:"):]))
        if not paths:
            raise FileNotFoundError(f"no files match {data_args.dataset!r}")
        blocks = tokens_from_text_files(paths, block_size, data_args.tokenizer_name)
    elif data_args.dataset.startswith("bin:"):
        # glob + per-shard block cut (tail below one block dropped per shard),
        # matching the native loader's layout exactly
        dtype = np.dtype(data_args.bin_dtype)
        shards = [
            TokenDataset.from_bin(p, block_size, dtype).blocks
            for p in _bin_paths(data_args.dataset)
        ]
        blocks = np.concatenate([s for s in shards if len(s)]) if shards else shards
    else:
        raise ValueError(f"unknown dataset spec {data_args.dataset!r}")

    if len(blocks):
        sample = np.asarray(blocks[: max(1, VOCAB_PROBE_TOKENS // blocks.shape[1])])
        _check_vocab(int(sample.max()), vocab_size)

    # validation split + debug truncation (run_clm.py:181-203, 355-381)
    n_val = max(1, len(blocks) * data_args.validation_split_percentage // 100)
    train, val = blocks[n_val:], blocks[:n_val]
    if data_args.max_train_samples:
        train = train[: data_args.max_train_samples]
    if data_args.max_eval_samples:
        val = val[: data_args.max_eval_samples]
    return np.asarray(train), np.asarray(val)


def make_native_pipeline(
    data_args: DataArguments, block_size: int, vocab_size: int,
    global_batch: int, seed: int,
):
    """C++ mmap+prefetch input pipeline for ``bin:<glob>`` datasets. Returns
    (train_iter, eval_blocks, loader) or None to fall back to Python."""
    if not (data_args.dataset.startswith("bin:") and data_args.native_loader):
        return None
    import numpy as np

    from distributed_lion_tpu.data.native_loader import (
        NativeTokenLoader,
        native_available,
    )

    if not native_available():
        print("[run_clm] no C++ toolchain; falling back to Python loader")
        return None
    paths = _bin_paths(data_args.dataset)
    loader = NativeTokenLoader(
        paths, block_size, dtype=np.dtype(data_args.bin_dtype)
    )
    n = len(loader)
    # hold-out range is ALWAYS the full split percentage so the training set
    # is identical whether or not --max_eval_samples caps the blocks actually
    # evaluated (and identical to the Python load_blocks path).
    n_val = max(1, n * data_args.validation_split_percentage // 100)
    hi = n
    if data_args.max_train_samples:
        hi = min(n, n_val + data_args.max_train_samples)
    # an explicit --max_eval_samples is honored in full; the 4096 default cap
    # only bounds the eager read on huge unconfigured splits (noted below)
    if data_args.max_eval_samples:
        n_eval_read = min(n_val, data_args.max_eval_samples)
    else:
        n_eval_read = min(n_val, 4096)
        if n_eval_read < n_val:
            print(f"[run_clm] eval uses the first {n_eval_read} of {n_val} "
                  "held-out blocks (set --max_eval_samples to override)")
    eval_blocks = loader.read_blocks(0, n_eval_read)
    # vocab check must also sample the TRAIN range — eval-only coverage would
    # let out-of-range train ids reach XLA gather's silent clamp.
    n_probe = max(1, min(hi - n_val, VOCAB_PROBE_TOKENS // block_size))
    probe_idx = np.linspace(n_val, hi - 1, n_probe, dtype=np.int64)
    mx = max(
        int(eval_blocks.max()) if n_eval_read else 0,
        max(int(loader.read_block(int(i)).max()) for i in probe_idx),
    )
    _check_vocab(mx, vocab_size)
    it = loader.batches(global_batch, seed=seed, block_range=(n_val, hi))
    print(f"[run_clm] native loader: {len(paths)} shard(s), {n} blocks "
          f"({n_val} held out for eval)")
    return it, eval_blocks, loader


def main(argv=None):
    from distributed_lion_tpu.utils.argparsing import parse_dataclasses

    model_args, data_args, train_cfg = parse_dataclasses(
        (ModelArguments, DataArguments, _train_config_cls()), argv
    )

    import jax.numpy as jnp

    from distributed_lion_tpu.data.sources import batch_iterator
    from distributed_lion_tpu.models.gpt2 import GPT2Config
    from distributed_lion_tpu.train.loop import Trainer

    mesh = build_mesh(train_cfg.tensor_parallel, train_cfg.seq_parallel,
                      train_cfg.pipeline_parallel, train_cfg.expert_parallel)
    dtypes = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}
    family = model_args.model_family
    if model_args.model_path:
        # the checkpoint's architecture wins; resolve BEFORE the family
        # guards so they judge what will actually run
        from distributed_lion_tpu.models import hf_import

        family = hf_import.detect_family(model_args.model_path)
        if family != model_args.model_family:
            print(f"[run_clm] --model_family {model_args.model_family} -> "
                  f"{family} (detected from --model_path)")
    dropout = resolve_dropout(model_args.dropout, family,
                              train_cfg.pipeline_parallel,
                              train_cfg.seq_parallel)
    common = dict(
        dropout=dropout,
        param_dtype=dtypes[model_args.param_dtype],
        compute_dtype=dtypes[model_args.compute_dtype],
        remat=model_args.remat,
        remat_policy=model_args.remat_policy,
        seq_impl=model_args.seq_impl,
        moe_experts=model_args.moe_experts,
        moe_every=model_args.moe_every,
        moe_capacity_factor=model_args.moe_capacity_factor,
        vocab_pad_multiple=model_args.vocab_pad_multiple,
    )
    if family not in ("gpt2", "llama"):
        raise ValueError(f"unknown model family {family!r}")
    if family == "llama" and (
        model_args.moe_experts > 0 or train_cfg.expert_parallel > 1
    ):
        raise NotImplementedError(
            "--model_family llama composes with dp x tp x sp x pp; MoE and "
            "the expert axis are wired for GPT-2 only"
        )
    if family == "llama" and (model_args.dropout or 0.0) > 0.0:
        raise ValueError("our Llama (like HF's) has no dropout; set --dropout 0")
    if family == "llama" and model_args.vocab_pad_multiple:
        raise ValueError(
            "--vocab_pad_multiple is a GPT-2 layout option; Llama vocabs "
            "(32000/128256) are already 128-multiples"
        )
    initial_params = None
    if model_args.model_path:
        if family == "llama":
            initial_params, model_cfg = hf_import.llama_from_hf(
                model_args.model_path,
                param_dtype=dtypes[model_args.param_dtype],
                compute_dtype=dtypes[model_args.compute_dtype],
                remat=model_args.remat,
                seq_impl=model_args.seq_impl,
            )
        else:
            initial_params, model_cfg = hf_import.gpt2_from_hf(
                model_args.model_path,
                dropout=dropout,
                param_dtype=dtypes[model_args.param_dtype],
                compute_dtype=dtypes[model_args.compute_dtype],
                remat=model_args.remat,
                seq_impl=model_args.seq_impl,
            )
        print(f"[run_clm] loaded pretrained {family} from {model_args.model_path}: "
              f"{model_cfg.n_layer}L d={model_cfg.d_model} vocab={model_cfg.vocab_size}")
        if model_args.vocab_pad_multiple:
            # pad the imported table with zero rows to the aligned layout;
            # hf_export slices them back off (models/gpt2 vocab_pad_multiple)
            from distributed_lion_tpu.models.gpt2 import pad_wte

            model_cfg = dataclasses.replace(
                model_cfg, vocab_pad_multiple=model_args.vocab_pad_multiple)
            initial_params["wte"] = pad_wte(initial_params["wte"], model_cfg)
    elif family == "llama":
        from distributed_lion_tpu.models.llama import LlamaConfig

        # the gpt2 `common` kwargs minus the fields LlamaConfig doesn't have
        # (dropout, moe_*)
        llama_common = {k: common[k] for k in
                        ("param_dtype", "compute_dtype", "remat",
                         "remat_policy", "seq_impl")}
        model_cfg = LlamaConfig.named(model_args.model_name, **llama_common)
    elif model_args.model_name == "tiny":
        model_cfg = GPT2Config.tiny(**common)
    elif model_args.model_name == "gpt2_small":
        model_cfg = GPT2Config.small(**common)
    else:
        model_cfg = GPT2Config.gpt2_124m(**common)
    if model_args.model_path and (model_args.vocab_size or model_args.n_ctx):
        raise ValueError("--vocab_size/--n_ctx cannot override a loaded checkpoint's architecture")
    if model_args.vocab_size:
        model_cfg = dataclasses.replace(model_cfg, vocab_size=model_args.vocab_size)
    elif data_args.dataset.startswith("text:") and initial_params is None:
        # (with a loaded checkpoint the embedding is fixed; out-of-range
        # tokenizer ids are caught by the _check_vocab probe instead)
        # size the embedding to the tokenizer when the user didn't pin it
        from distributed_lion_tpu.data.tokenizer import load_tokenizer

        tok_vocab = load_tokenizer(data_args.tokenizer_name).vocab_size
        if tok_vocab > model_cfg.vocab_size:
            print(f"[run_clm] growing vocab_size {model_cfg.vocab_size} -> tokenizer {tok_vocab}")
            model_cfg = dataclasses.replace(model_cfg, vocab_size=tok_vocab)
    if model_args.n_ctx:
        model_cfg = dataclasses.replace(model_cfg, n_ctx=model_args.n_ctx)
    if model_args.hf_export and getattr(model_cfg, "moe_experts", 0) > 0:
        # fail BEFORE spending the training budget: MoE blocks have no HF
        # GPT-2 equivalent (models/hf_export raises the same at save time)
        raise ValueError("--hf_export is incompatible with --moe_experts: "
                         "MoE blocks have no HF GPT-2 equivalent")
    if train_cfg.block_size > model_cfg.n_ctx:
        # run_clm.py:491-506 caps block_size at the model context length.
        print(f"[run_clm] capping block_size {train_cfg.block_size} -> n_ctx {model_cfg.n_ctx}")
        train_cfg.block_size = model_cfg.n_ctx

    factory = Trainer.for_llama if family == "llama" else Trainer.for_gpt2
    trainer = factory(train_cfg, mesh, model_cfg, initial_params=initial_params)
    if train_cfg.telemetry:
        # name the regime the vote-health records will be in: only the
        # tally wires carry exact margins; the ±1-proxy wires zero the
        # histogram by design (train/telemetry.tally_wire)
        from distributed_lion_tpu.train.telemetry import tally_wire

        print("[run_clm] vote-health telemetry on: margin histogram "
              + ("EXACT (tally wire "
                 if tally_wire(trainer.cfg.wire) else "UNAVAILABLE (proxy wire ")
              + f"{trainer.cfg.wire}); drained every "
              f"{train_cfg.logging_steps} steps"
              + (", NaN sentinel armed" if train_cfg.nan_sentinel else ""))
    if train_cfg.vote_guard != "off":
        world = trainer.world
        # the guard's OWN resolved quorum — never re-derive the auto rule
        quorum = trainer._guard.min_quorum
        print(f"[run_clm] vote guard {train_cfg.vote_guard.upper()}: "
              f"per-worker ballot health inside the step (nonfinite / "
              f"frozen / outlier), quarantine after {train_cfg.guard_strikes}"
              f" strikes, readmission probe after {train_cfg.guard_cooldown} "
              f"steps, refusing below quorum {quorum}/{world}"
              + ("" if train_cfg.vote_guard == "enforce"
                 else " (observe: elections untouched)"))
    native = make_native_pipeline(
        data_args, train_cfg.block_size, model_cfg.vocab_size,
        trainer.global_train_batch(), train_cfg.seed,
    )
    if native is not None:
        it, eval_blocks, _loader = native
        # stamp the SERVED shard fleet into every checkpoint's manifest
        # meta: block indexing is a pure function of this list, so a
        # resumed run must see the identical fleet or its deterministic
        # replay (the batches_consumed fast-forward) silently streams
        # different data than the original run consumed
        trainer.data_meta["data_shards"] = _loader.shards
        if trainer.step_count > 0:
            meta = (trainer.checkpointer.manifest_meta(trainer.step_count)
                    if trainer.checkpointer and train_cfg.ckpt_integrity
                    else None) or {}
            old = meta.get("data_shards")
            if old is not None and list(old) != list(_loader.shards):
                raise RuntimeError(
                    f"resuming from step {trainer.step_count} but the "
                    f"served shard fleet changed: checkpoint recorded "
                    f"{old}, this run would serve {_loader.shards} "
                    f"(skipped: {_loader.skipped_shards}); the "
                    "deterministic data replay would diverge from the "
                    "original run. Restore the original shards (or start "
                    "fresh with --resume_from_checkpoint false / a new "
                    "--output_dir)")
            if old is None and _loader.skipped_shards:
                # pre-stamp checkpoint (or integrity off): the original
                # fleet is unknown and THIS run's fleet just shrank —
                # refuse conservatively rather than risk a divergent replay
                raise RuntimeError(
                    f"resuming from step {trainer.step_count} but "
                    f"{len(_loader.skipped_shards)} shard(s) failed to "
                    f"load ({_loader.skipped_shards}) and the checkpoint "
                    "predates shard-fleet stamping — cannot prove the "
                    "deterministic replay matches. Restore the shard(s) "
                    "(or start fresh with --resume_from_checkpoint false "
                    "/ a new --output_dir)")
    else:
        train_blocks, eval_blocks = load_blocks(
            data_args, train_cfg.block_size, model_cfg.vocab_size
        )
        it = batch_iterator(train_blocks, trainer.global_train_batch(), seed=train_cfg.seed)
    try:
        trainer.train(it, eval_blocks=eval_blocks)
        if trainer.preempted:
            # drained + emergency checkpoint already durable; exit 0 so the
            # watcher restarts this command into a normal resume
            print("[run_clm] preempted: "
                  + ("checkpoint durable, " if trainer.checkpointer
                     else "NO checkpointer (no --output_dir) — nothing "
                          "saved, ")
                  + "exiting cleanly")
            return
        if eval_blocks is not None and len(eval_blocks):
            trainer.evaluate(eval_blocks)
        if trainer.checkpointer:
            trainer.save()
        if train_cfg.output_dir or model_args.hf_export:
            export = trainer.params
            if train_cfg.pipeline_parallel > 1:
                if family == "gpt2":
                    from distributed_lion_tpu.models.gpt2_pipe import (
                        unpipeline_params)

                    export = unpipeline_params(export, model_cfg.n_layer)
                else:
                    from distributed_lion_tpu.models.llama_pipe import (
                        llama_unpipeline_params)

                    export = llama_unpipeline_params(export, model_cfg.n_layer)
        if train_cfg.output_dir:
            # portable single-file export (HF save_pretrained role) —
            # consumed by cli/run_generate
            from distributed_lion_tpu.utils.serialization import save_pytree

            save_pytree(f"{train_cfg.output_dir}/model.npz", export)
        if model_args.hf_export:
            # HF save_pretrained layout (run_clm.py:611-622's save_model;
            # loadable by GPT2LMHeadModel.from_pretrained) — dense
            # architectures only (guarded before training starts)
            import jax

            from distributed_lion_tpu.models.hf_export import (
                copy_tokenizer_files,
                gpt2_to_hf,
                llama_to_hf,
                write_model_card,
            )

            to_hf = llama_to_hf if family == "llama" else gpt2_to_hf
            to_hf(jax.device_get(export), model_cfg, model_args.hf_export)
            copy_tokenizer_files(data_args.tokenizer_name, model_args.hf_export)
            write_model_card(
                model_args.hf_export, model_type=family,
                train_summary={
                    "optimizer": "distributed-lion" if train_cfg.lion else "adamw",
                    "async_grad": train_cfg.async_grad,
                    # trainer.cfg, not train_cfg: the card must record the
                    # wire that actually ran, not the 'auto' sentinel
                    "wire": trainer.cfg.wire,
                    "vote_every": trainer.cfg.vote_every,
                    "steps": train_cfg.max_steps,
                    "learning_rate": train_cfg.learning_rate,
                    "weight_decay": train_cfg.weight_decay,
                    "global_batch": trainer.global_train_batch(),
                    "block_size": train_cfg.block_size,
                    "n_params": trainer.n_params,
                },
            )
            print(f"[run_clm] HF-format checkpoint at {model_args.hf_export}")
    finally:
        trainer.close()


def _train_config_cls():
    from distributed_lion_tpu.train.loop import TrainConfig

    return TrainConfig


if __name__ == "__main__":
    main()
