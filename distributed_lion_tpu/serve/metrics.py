"""Host-side request-lifecycle metrics plane for the serving stack.

Layering contract (same as train/journal.py): stdlib + numpy ONLY — no
jax import anywhere in this module, so crash tooling, analyzers and the
workload generator can import it on machines with no accelerator stack.
Nothing here may add a device sync: every stamp rides host work the tick
loop already does (``submit`` bookkeeping, the one ``np.asarray`` host
read per decode tick, completion assembly). The engine's token path is
byte-identical with metrics on or off — pinned by the bit-identity
matrix in tests/test_serve_metrics.py and the ``metrics_inert`` marker
of serving.json's ``slo`` section.

Three layers:

``LogHistogram``
    A bounded incremental percentile sketch: fixed geometric bins
    (``bins_per_decade`` bins per decade between ``lo`` and ``hi``),
    exact count/sum/min/max on the side. ``merge`` is associative and
    commutative (pure bin-count addition), so ``ServingFleet`` can
    aggregate per-replica sketches without ever holding raw samples.
    A percentile query returns the geometric midpoint of the bin the
    rank falls in, clamped to the observed [min, max]: the relative
    error is bounded by the bin ratio ``10**(1/bins_per_decade)``
    (pinned against a numpy reference in tests).

``RequestTimes`` / ``ServeMetrics``
    ``RequestTimes`` is the always-on tick-domain clock: per-request
    submit/first-token/finish tick stamps that become the
    ``ttft_ticks`` / ``queue_ticks`` / ``decode_ticks`` fields on every
    serve/api response record (serve/api.completion_record). It is
    integer bookkeeping on host events that already happen, so it runs
    unconditionally. ``ServeMetrics`` is the opt-in plane on top: wall
    clocks (TTFT ms, per-token decode ms), the sketches, live gauges
    (queue depth, page-pool occupancy, active slots, speculative
    accept rate, prefix-hit/CoW counts, evictions), drained at a tick
    cadence into ``serve_metrics`` journal events (train/journal.py —
    strict JSON, ``allow_nan=False``).

``SLOMonitor``
    Rolling-window burn-rate accounting over per-request SLO outcomes
    (``--slo_ttft_ms`` / ``--slo_tok_ms`` / ``--slo_p99``). The error
    budget is ``1 - slo_p99``; burn rate is the window's violation
    fraction divided by that budget. Crossing 1.0 journals an
    ``slo_breach`` event (edge-triggered, so a sustained breach is one
    event, not one per request) and counts honestly in ``breaches``.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

import numpy as np

from distributed_lion_tpu.train import journal as journal_mod


# ---------------------------------------------------------------------------
# percentile sketch
# ---------------------------------------------------------------------------


class LogHistogram:
    """Fixed-bin log-scale percentile sketch — bounded and mergeable.

    Bins are geometric: bin ``i`` (1-based interior) covers
    ``[lo * base**(i-1), lo * base**i)`` with
    ``base = 10**(1/bins_per_decade)``. Bin 0 is the underflow bucket
    (values <= lo, including zeros), the last bin the overflow bucket
    (values >= hi). The memory footprint is fixed at construction —
    independent of how many samples are added — which is the whole
    point: a million-request soak costs the same bytes as ten requests.
    """

    def __init__(self, lo: float = 1e-3, hi: float = 1e7,
                 bins_per_decade: int = 32):
        if not (lo > 0 and hi > lo):
            raise ValueError(f"need 0 < lo < hi, got lo={lo!r} hi={hi!r}")
        if bins_per_decade < 1:
            raise ValueError(f"bins_per_decade must be >= 1, got "
                             f"{bins_per_decade!r}")
        self.lo = float(lo)
        self.hi = float(hi)
        self.bins_per_decade = int(bins_per_decade)
        decades = math.log10(self.hi / self.lo)
        self._interior = int(math.ceil(decades * self.bins_per_decade))
        # [underflow] + interior + [overflow]
        self.counts = np.zeros(self._interior + 2, dtype=np.int64)
        self.n = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    # -- construction-compatibility key for merge ------------------------
    def _key(self):
        return (self.lo, self.hi, self.bins_per_decade)

    def _bin_of(self, v: float) -> int:
        if v <= self.lo:
            return 0
        if v >= self.hi:
            return len(self.counts) - 1
        i = 1 + int(math.floor(
            math.log10(v / self.lo) * self.bins_per_decade))
        return min(max(i, 1), self._interior)

    def add(self, value: float, count: int = 1) -> None:
        """Record ``count`` observations of ``value``. Non-finite values
        are refused loudly — a NaN latency is a bug upstream, and a
        sketch that silently eats it would launder the bug into every
        percentile it ever reports."""
        v = float(value)
        if not math.isfinite(v):
            raise ValueError(f"non-finite sample {value!r}")
        if count < 1:
            raise ValueError(f"count must be >= 1, got {count!r}")
        self.counts[self._bin_of(v)] += count
        self.n += count
        self.total += v * count
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Pure merge: returns a NEW sketch holding both inputs' mass.
        Associative and commutative (bin-count addition), so a fleet can
        fold replicas in any order and get identical counts."""
        if other._key() != self._key():
            raise ValueError(
                f"cannot merge sketches with different layouts: "
                f"{self._key()} vs {other._key()}")
        out = LogHistogram(self.lo, self.hi, self.bins_per_decade)
        out.counts = self.counts + other.counts
        out.n = self.n + other.n
        out.total = self.total + other.total
        out.vmin = min(self.vmin, other.vmin)
        out.vmax = max(self.vmax, other.vmax)
        return out

    def percentile(self, q: float) -> float:
        """Value at quantile ``q`` (0..100): geometric midpoint of the
        bin the rank falls in, clamped to the observed [min, max]. With
        no samples, 0.0 (a sketch with nothing in it has no latency to
        report — callers gate on ``n``)."""
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"q must be in [0, 100], got {q!r}")
        if self.n == 0:
            return 0.0
        rank = max(1, int(math.ceil(q / 100.0 * self.n)))
        cum = 0
        idx = len(self.counts) - 1
        for i, c in enumerate(self.counts):
            cum += int(c)
            if cum >= rank:
                idx = i
                break
        if idx == 0:
            # underflow holds values <= lo: the observed min is the only
            # honest representative (lo itself may never have occurred)
            rep = self.vmin
        elif idx == len(self.counts) - 1:
            rep = self.vmax
        else:
            edge_lo = self.lo * 10.0 ** ((idx - 1) / self.bins_per_decade)
            edge_hi = self.lo * 10.0 ** (idx / self.bins_per_decade)
            rep = math.sqrt(edge_lo * edge_hi)
        return float(min(max(rep, self.vmin), self.vmax))

    def summary(self) -> Dict[str, float]:
        """Flat strict-JSON-safe summary (what drain journals and the
        bench banks)."""
        if self.n == 0:
            return {"count": 0, "min": 0.0, "max": 0.0, "mean": 0.0,
                    "p50": 0.0, "p95": 0.0, "p99": 0.0}
        return {"count": int(self.n),
                "min": float(self.vmin), "max": float(self.vmax),
                "mean": float(self.total / self.n),
                "p50": self.percentile(50.0),
                "p95": self.percentile(95.0),
                "p99": self.percentile(99.0)}


class TickLatencyWindow:
    """Bounded tick-latency diagnostic: a recency window of raw samples
    (exact percentiles over the last ``window`` ticks — what the slow-
    replica bench reads) plus a full-history :class:`LogHistogram` for
    fleet-level merging. Replaces the unbounded per-replica
    ``tick_latency_log`` lists (a soak of millions of ticks used to grow
    a float per tick per replica, forever)."""

    def __init__(self, window: int = 1024):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window!r}")
        self.recent: Deque[float] = deque(maxlen=int(window))
        self.sketch = LogHistogram()

    def add(self, ms: float) -> None:
        self.recent.append(float(ms))
        self.sketch.add(float(ms))

    def __len__(self) -> int:
        return self.sketch.n

    def percentile(self, q: float) -> float:
        """Exact percentile over the recency window (numpy reference on
        the bounded raw samples; the sketch answers full-history
        queries)."""
        if not self.recent:
            return 0.0
        return float(np.percentile(list(self.recent), q))


# ---------------------------------------------------------------------------
# request lifecycle clocks
# ---------------------------------------------------------------------------


class RequestTimes:
    """Always-on tick-domain request clocks. One small dict per inflight
    request; entries retire on ``finished``, so steady-state memory is
    bounded by the number of inflight requests, not the soak length.

    Stamp taxonomy (ticks are the engine's own loop counter):

    - ``submit_tick``  — admission-queue entry (ServingEngine.submit)
    - ``first_tick``   — the tick whose prefill produced token 0 (TTFT)
    - ``finish_tick``  — terminal tick (eos/length/overflow/timeout/
      failed — every status stamps, including queue-side deaths that
      never reached prefill)

    Derived fields (the serve/api response-record columns):
    ``queue_ticks = first_tick - submit_tick`` (admission wait),
    ``ttft_ticks`` (same clock — they diverge only if prefill is ever
    chunked across ticks), ``decode_ticks = finish_tick - first_tick``.
    """

    def __init__(self):
        self._submit: Dict[Any, int] = {}
        self._first: Dict[Any, int] = {}

    def submitted(self, req_id, tick: int) -> None:
        self._submit.setdefault(req_id, int(tick))

    def first_token(self, req_id, tick: int) -> None:
        self._first.setdefault(req_id, int(tick))

    def finished(self, req_id, tick: int) -> Dict[str, int]:
        """Retire the request's clocks; returns the timing dict that
        rides the Completion (and from there the response record)."""
        tick = int(tick)
        sub = self._submit.pop(req_id, tick)
        first = self._first.pop(req_id, None)
        if first is None:
            # never produced a token (queue-side timeout/failure):
            # the whole life was queue wait, decode never started
            return {"queue_ticks": max(tick - sub, 0), "decode_ticks": 0}
        return {"queue_ticks": max(first - sub, 0),
                "ttft_ticks": max(first - sub, 0),
                "decode_ticks": max(tick - first, 0)}


# ---------------------------------------------------------------------------
# SLO monitor
# ---------------------------------------------------------------------------


class SLOMonitor:
    """Rolling-window burn-rate accounting over per-request outcomes.

    A finished request is in-SLO when its TTFT is within ``ttft_ms``
    AND its mean per-token decode latency is within ``tok_ms`` (either
    bound may be None = unmonitored). The error budget is
    ``1 - slo_p99`` — the violation fraction the SLO tolerates; burn
    rate is the rolling window's violation fraction divided by that
    budget, so 1.0 means "spending budget exactly as fast as allowed".
    Crossing above 1.0 (with at least ``min_count`` requests in the
    window) journals one edge-triggered ``slo_breach`` event and
    increments ``breaches``.
    """

    def __init__(self, ttft_ms: Optional[float] = None,
                 tok_ms: Optional[float] = None, p99: float = 0.99,
                 window: int = 256, min_count: int = 8):
        if not 0.0 < p99 < 1.0:
            raise ValueError(f"slo_p99 must be in (0, 1), got {p99!r}")
        self.ttft_ms = None if ttft_ms is None else float(ttft_ms)
        self.tok_ms = None if tok_ms is None else float(tok_ms)
        self.p99 = float(p99)
        self.min_count = int(min_count)
        self._window: Deque[bool] = deque(maxlen=int(window))
        self.requests = 0
        self.violations = 0
        self.violations_ttft = 0
        self.violations_tok = 0
        self.breaches = 0
        self._breached = False

    @property
    def error_budget(self) -> float:
        return 1.0 - self.p99

    def burn_rate(self) -> float:
        if not self._window:
            return 0.0
        frac = sum(self._window) / len(self._window)
        return frac / self.error_budget

    def observe(self, ttft_ms: Optional[float],
                mean_tok_ms: Optional[float], *, tick: int = 0) -> bool:
        """Record one finished request; returns True if it violated the
        SLO. A request that never produced a token (``ttft_ms`` None
        under a monitored TTFT bound) counts as a violation — the
        honest reading of "the user never saw a first token"."""
        bad_ttft = self.ttft_ms is not None and (
            ttft_ms is None or ttft_ms > self.ttft_ms)
        bad_tok = self.tok_ms is not None and (
            mean_tok_ms is not None and mean_tok_ms > self.tok_ms)
        bad = bad_ttft or bad_tok
        self.requests += 1
        if bad_ttft:
            self.violations_ttft += 1
        if bad_tok:
            self.violations_tok += 1
        if bad:
            self.violations += 1
        self._window.append(bad)
        rate = self.burn_rate()
        if (rate > 1.0 and len(self._window) >= self.min_count
                and not self._breached):
            self._breached = True
            self.breaches += 1
            journal_mod.event(
                "slo_breach", tick=int(tick), burn_rate=float(rate),
                window=len(self._window),
                window_violations=int(sum(self._window)),
                error_budget=float(self.error_budget))
        elif rate <= 1.0:
            self._breached = False
        return bad

    def snapshot(self) -> Dict[str, float]:
        return {"requests": int(self.requests),
                "violations": int(self.violations),
                "violations_ttft": int(self.violations_ttft),
                "violations_tok": int(self.violations_tok),
                "breaches": int(self.breaches),
                "burn_rate": float(self.burn_rate()),
                "error_budget": float(self.error_budget)}


# ---------------------------------------------------------------------------
# the per-engine metrics plane
# ---------------------------------------------------------------------------


class ServeMetrics:
    """Opt-in request-lifecycle metrics for one engine (or one replica).

    Owns the wall clocks and sketches; reads tick stamps from the
    engine's always-on :class:`RequestTimes`. All hooks are plain host
    arithmetic on events the tick loop already pays for — no hook may
    touch a device value that is not already a host scalar (the DLT001
    graft rule; tests/fixtures/analysis/serve/dlt001_metrics_host_read
    .py shows the forbidden shape).
    """

    def __init__(self, times: RequestTimes,
                 slo: Optional[SLOMonitor] = None,
                 drain_every: int = 64, time_fn=time.monotonic):
        if drain_every < 1:
            raise ValueError(f"drain_every must be >= 1, got "
                             f"{drain_every!r}")
        self.times = times
        self.slo = slo
        self.drain_every = int(drain_every)
        self._now = time_fn
        self._submit_t: Dict[Any, float] = {}
        self._first_t: Dict[Any, float] = {}
        self.ttft_ms = LogHistogram()
        self.tok_ms = LogHistogram()
        self.ttft_ticks = LogHistogram(lo=0.5, hi=1e7, bins_per_decade=32)
        self.queue_ticks = LogHistogram(lo=0.5, hi=1e7, bins_per_decade=32)
        self.decode_ticks = LogHistogram(lo=0.5, hi=1e7,
                                         bins_per_decade=32)
        self.status_counts: Dict[str, int] = {}
        self.gauges: Dict[str, float] = {}
        self.drains = 0

    # -- lifecycle hooks (called from the engine's existing host work) --
    def on_submit(self, req_id) -> None:
        self._submit_t.setdefault(req_id, self._now())

    def on_first_token(self, req_id) -> None:
        if req_id in self._first_t:
            return
        t = self._now()
        self._first_t[req_id] = t
        t0 = self._submit_t.get(req_id)
        if t0 is not None:
            self.ttft_ms.add(max((t - t0) * 1e3, 0.0))

    def on_decode_tick(self, wall_ms: float, batch: int) -> None:
        """One decode dispatch produced one token for each of ``batch``
        active requests: the tick's wall time IS the per-token decode
        interval for every one of them."""
        if batch > 0:
            self.tok_ms.add(max(float(wall_ms), 0.0), count=int(batch))

    def on_finish(self, req_id, timing: Dict[str, int],
                  status: str, *, tick: int = 0) -> Dict[str, Any]:
        """Fold a terminal request into the sketches/SLO; returns the
        timing dict extended with wall ``ttft_ms`` when available."""
        self.status_counts[status] = self.status_counts.get(status, 0) + 1
        if "queue_ticks" in timing:
            self.queue_ticks.add(max(timing["queue_ticks"], 0.5))
        if "ttft_ticks" in timing:
            self.ttft_ticks.add(max(timing["ttft_ticks"], 0.5))
        if "decode_ticks" in timing:
            self.decode_ticks.add(max(timing["decode_ticks"], 0.5))
        t0 = self._submit_t.pop(req_id, None)
        t1 = self._first_t.pop(req_id, None)
        ttft = None
        if t0 is not None and t1 is not None:
            ttft = max((t1 - t0) * 1e3, 0.0)
            timing = dict(timing)
            timing["ttft_ms"] = float(ttft)
        if self.slo is not None:
            n_dec = max(int(timing.get("decode_ticks", 0)), 0)
            mean_tok = None
            if n_dec > 0 and t1 is not None:
                mean_tok = max((self._now() - t1) * 1e3, 0.0) / n_dec
            self.slo.observe(ttft, mean_tok, tick=tick)
        return timing

    def set_gauges(self, **gauges) -> None:
        """Replace the live gauge snapshot (queue depth, active slots,
        page-pool occupancy, accept/hit rates ... whatever the caller's
        stats surface exposes as host scalars)."""
        self.gauges = {k: float(v) for k, v in gauges.items()}

    # -- drain ----------------------------------------------------------
    def maybe_drain(self, tick: int) -> Optional[Dict[str, Any]]:
        if tick % self.drain_every != 0:
            return None
        return self.drain(tick)

    def drain(self, tick: int) -> Dict[str, Any]:
        """Emit the current snapshot as one ``serve_metrics`` journal
        event (flat strict-JSON fields) and return it."""
        self.drains += 1
        snap: Dict[str, Any] = {"tick": int(tick)}
        for name, sk in (("ttft_ms", self.ttft_ms),
                         ("tok_ms", self.tok_ms),
                         ("queue_ticks", self.queue_ticks),
                         ("decode_ticks", self.decode_ticks)):
            for k, v in sk.summary().items():
                snap[f"{name}_{k}"] = v
        for k, v in self.gauges.items():
            snap[f"gauge_{k}"] = v
        for k, v in sorted(self.status_counts.items()):
            snap[f"status_{k}"] = int(v)
        if self.slo is not None:
            for k, v in self.slo.snapshot().items():
                snap[f"slo_{k}"] = v
        journal_mod.event("serve_metrics", **snap)
        return snap

    # -- fleet aggregation ----------------------------------------------
    def merge_from(self, other: "ServeMetrics") -> None:
        """Fold another plane's sketches/counters into this one (the
        fleet-level aggregate). Raw samples never cross the boundary —
        only bin counts and counters."""
        self.ttft_ms = self.ttft_ms.merge(other.ttft_ms)
        self.tok_ms = self.tok_ms.merge(other.tok_ms)
        self.ttft_ticks = self.ttft_ticks.merge(other.ttft_ticks)
        self.queue_ticks = self.queue_ticks.merge(other.queue_ticks)
        self.decode_ticks = self.decode_ticks.merge(other.decode_ticks)
        for k, v in other.status_counts.items():
            self.status_counts[k] = self.status_counts.get(k, 0) + v

    def snapshot(self) -> Dict[str, Any]:
        """Nested summary (bench/report consumption; ``drain`` journals
        the flat form)."""
        out: Dict[str, Any] = {
            "ttft_ms": self.ttft_ms.summary(),
            "tok_ms": self.tok_ms.summary(),
            "queue_ticks": self.queue_ticks.summary(),
            "decode_ticks": self.decode_ticks.summary(),
            "status_counts": dict(sorted(self.status_counts.items())),
            "gauges": dict(self.gauges),
        }
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        return out
