"""Paged KV cache: a fixed page pool per layer + host-side block tables.

The vLLM PagedAttention design (Kwon et al., 2023) mapped onto the repo's
static-shape discipline: each layer's cache is ONE device array
``[num_blocks, block_size, kv_heads, head_dim]`` (the pool), and a
sequence owns an ordered list of page indices — its block table. All
allocation and free is HOST-side integer table math in this module; the
device never sees a dynamic shape, so the decode tick stays one jitted
program while sequences join and leave the batch (serve/engine.py). The
device-side scatter/gather/attend primitives live in
``ops.attention`` (``paged_scatter_kv`` / ``paged_gather_kv`` /
``paged_decode_attention``).

Sentinel convention: unallocated table entries hold ``num_blocks`` (one
past the pool). Scatters to a sentinel page drop (XLA scatter
``mode='drop'``), gathers from it fill zeros — inactive decode slots and
right-padded prefill tails are inert without a single host branch inside
the compiled tick.

Prefix sharing (ISSUE 13): every page carries a REFCOUNT. ``grow`` mints
ref-1 pages exactly as before; :meth:`BlockTables.share` points a slot's
leading table entries at pages another sequence (or the
:class:`PrefixCache`) already owns, bumping their refs; ``shrink`` /
``free_slot`` release refs and a page returns to the free list only at
ref 0 — so N requests carrying the same system prompt hold ONE physical
copy of its KV pages, and speculative rollback over a shared table row
releases refs without freeing pages a neighbor still reads. A write into
a ref>1 page is forbidden; the engine first calls :meth:`BlockTables.cow`
(copy-on-write: a fresh ref-1 page replaces the table entry, the device
copy rides ``ops.attention.paged_copy_pages``) so the first divergent
write targets a private copy — content-identical up to the written
suffix, bit-identity preserved by construction.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def init_pages(n_layer: int, num_blocks: int, block_size: int,
               kv_heads: int, head_dim: int, dtype) -> list:
    """The per-layer device page pool: ``[{"k", "v"}] * n_layer`` of
    zeros ``[num_blocks, block_size, kv_heads, head_dim]``. Allocated
    once at engine start — ticks update it in place (donated)."""
    import jax.numpy as jnp

    shape = (num_blocks, block_size, kv_heads, head_dim)
    return [
        {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        for _ in range(n_layer)
    ]


def bucket_tokens(n: int, block_size: int, max_blocks_per_seq: int) -> int:
    """Padded prefill length for an ``n``-token prompt: power-of-two
    pages, so prompt-length variety costs O(log(max)) compiles, not one
    per length. The ONE bucketing rule — the serving engine's prefill and
    the draft-model mirror's prefill (serve/speculate.py) must pad
    identically or the mirror desyncs. For MoE checkpoints the bucket
    also sizes the no-drop expert dispatch buffer ([E, bucket, D] per MoE
    block, models/gpt2._decode_mlp): pad lanes are valid-masked out of
    routing, so the bucket choice changes memory, never an output."""
    blocks = 1
    while blocks * block_size < n:
        blocks *= 2
    return min(blocks, max_blocks_per_seq) * block_size


class BlockTables:
    """Host-side page allocator + per-slot block tables.

    ``tables`` is the ``[max_seqs, max_blocks_per_seq]`` int32 array the
    engine ships to the device each tick (sentinel-padded); ``owned[slot]``
    counts the pages slot currently holds. Pure numpy/stdlib — this is
    the "allocation is host-side table math, never a recompile" half of
    the paged design, and it must stay importable without jax for the
    bench's capacity planning.
    """

    def __init__(self, num_blocks: int, block_size: int, max_seqs: int,
                 max_blocks_per_seq: int, groups: int = 1):
        if num_blocks < 1 or block_size < 1:
            raise ValueError(
                f"need positive pool dims, got num_blocks={num_blocks} "
                f"block_size={block_size}")
        if groups < 1 or num_blocks % groups or max_seqs % groups:
            raise ValueError(
                f"groups={groups} must divide num_blocks={num_blocks} and "
                f"max_seqs={max_seqs}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_seqs = int(max_seqs)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.sentinel = self.num_blocks
        # Batch-sharded expert-parallel serving (ISSUE 16) partitions the
        # pool into ``groups`` contiguous spans: group g owns pages
        # [g*bpg, (g+1)*bpg) and slots [g*spg, (g+1)*spg) — each device
        # shard holds exactly one group's pages, so a slot's table entries
        # (minus the group base) are valid LOCAL page ids on its shard.
        self.groups = int(groups)
        self.blocks_per_group = self.num_blocks // self.groups
        self.slots_per_group = self.max_seqs // self.groups
        # per-group LIFO free lists: recently-freed pages are re-used
        # first, which keeps the working set of the pool small and
        # cache-warm. groups=1 is bit-identical to the historical single
        # list (same pop/append order).
        bpg = self.blocks_per_group
        self._free = [list(range((g + 1) * bpg - 1, g * bpg - 1, -1))
                      for g in range(self.groups)]
        self.tables = np.full((max_seqs, max_blocks_per_seq), self.sentinel,
                              np.int32)
        self.owned = np.zeros((max_seqs,), np.int32)
        # per-page refcounts: a table entry AND a PrefixCache registration
        # each hold one ref; a page is free iff refs == 0 (then it sits on
        # the free list). pages_allocated counts every mint (grow pops +
        # CoW pops) — the bench's physical-page ledger.
        self.refs = np.zeros((self.num_blocks,), np.int32)
        self.pages_allocated = 0

    # ------------------------------------------------------------ capacity
    @property
    def free_blocks(self) -> int:
        return sum(len(f) for f in self._free)

    def group_of(self, slot: int) -> int:
        """The pool group ``slot`` allocates from (its device shard under
        batch-sharded ep; group 0 covers everything when groups == 1)."""
        return int(slot) // self.slots_per_group

    def group_base(self, group: int) -> int:
        """First page id of ``group``'s pool span — subtract it from a
        table entry to get the shard-LOCAL page id."""
        return int(group) * self.blocks_per_group

    def free_blocks_in(self, group: int) -> int:
        return len(self._free[group])

    @property
    def max_tokens_per_seq(self) -> int:
        return self.max_blocks_per_seq * self.block_size

    def blocks_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` cache entries."""
        return -(-max(n_tokens, 0) // self.block_size)

    def can_grow(self, slot: int, n_tokens: int) -> bool:
        """Would :meth:`grow` succeed for ``n_tokens`` total tokens?"""
        need = self.blocks_for(n_tokens)
        if need > self.max_blocks_per_seq:
            return False
        return need - int(self.owned[slot]) <= len(
            self._free[self.group_of(slot)])

    # ---------------------------------------------------------- alloc/free
    def _mint(self, group: int = 0) -> int:
        """Pop a fresh page off ``group``'s free list at ref 1 (counted)."""
        p = self._free[group].pop()
        assert self.refs[p] == 0, f"page {p} on the free list with refs"
        self.refs[p] = 1
        self.pages_allocated += 1
        return p

    def _release(self, page: int) -> int:
        """Drop one ref; the page returns to its group's LIFO free list
        only at ref 0. Returns 1 when the page was physically freed."""
        page = int(page)
        assert self.refs[page] > 0, f"double free of page {page}"
        self.refs[page] -= 1
        if self.refs[page] == 0:
            self._free[page // self.blocks_per_group].append(page)
            return 1
        return 0

    def grow(self, slot: int, n_tokens: int) -> bool:
        """Ensure ``slot``'s table covers ``n_tokens`` total cache
        entries, allocating pages as needed. Returns False (allocating
        NOTHING — all-or-nothing, so a half-grown slot can't strand
        pages) when the pool or the table width can't fit it."""
        if not self.can_grow(slot, n_tokens):
            return False
        need = self.blocks_for(n_tokens)
        have = int(self.owned[slot])
        if need <= have:
            # grow never shrinks: writing owned = need here would orphan
            # the tail pages' refs (table entries past owned are invisible
            # to every release path) — the refcount fuzz test caught this
            return True
        g = self.group_of(slot)
        for i in range(have, need):
            self.tables[slot, i] = self._mint(g)
        self.owned[slot] = need
        return True

    def shrink(self, slot: int, n_tokens: int) -> int:
        """Release ``slot``'s pages beyond those ``n_tokens`` total cache
        entries need — the EXACT inverse of :meth:`grow`: ref-1 pages
        return to the LIFO free list in reverse allocation order, so
        ``grow(slot, a); shrink(slot, b)`` leaves the allocator (tables,
        owned, free-list order) bit-identical to ``grow(slot, b)`` for any
        ``b <= a``. This is the speculative-decode rollback primitive
        (serve/speculate.py): a verify window optimistically grows the
        table for k draft tokens and the rejected tail's pages are handed
        back as if they were never allocated, so the post-commit state
        matches what a token-by-token run would hold (tests/test_serve.py
        pins it). SHARED tail pages (refs > 1 — a rollback over a shared
        prefix) only drop this slot's ref: the physical page survives for
        its other holders. Returns the count of pages physically freed."""
        need = self.blocks_for(n_tokens)
        have = int(self.owned[slot])
        if need >= have:
            return 0
        freed = 0
        for i in range(have - 1, need - 1, -1):
            freed += self._release(self.tables[slot, i])
            self.tables[slot, i] = self.sentinel
        self.owned[slot] = need
        return freed

    def free_slot(self, slot: int) -> int:
        """Release all of ``slot``'s refs; the table row goes back to
        sentinel (inert on device). Returns the count of pages physically
        freed — evicting a sharer whose pages all outlive it (the prefix
        cache or another slot still holds them) frees ZERO pages, and the
        engine's accounting must say so."""
        n = int(self.owned[slot])
        freed = 0
        for i in range(n):
            freed += self._release(self.tables[slot, i])
        self.tables[slot, :] = self.sentinel
        self.owned[slot] = 0
        return freed

    def find_free_slot(self) -> Optional[int]:
        """Lowest slot index owning zero pages (the engine marks a slot
        occupied by growing it; completed slots are freed)."""
        for s in range(self.max_seqs):
            if self.owned[s] == 0:
                return s
        return None

    # ------------------------------------------------------ prefix sharing
    def share(self, slot: int, pages: list) -> None:
        """Point an EMPTY slot's leading table entries at already-owned
        pages (a prefix-cache hit), taking one ref per page. ``grow`` then
        extends the row with fresh private pages as usual."""
        if int(self.owned[slot]) != 0:
            raise ValueError(
                f"share() needs an empty slot, slot {slot} owns "
                f"{int(self.owned[slot])} pages")
        if len(pages) > self.max_blocks_per_seq:
            raise ValueError(
                f"shared run of {len(pages)} pages exceeds the table "
                f"width {self.max_blocks_per_seq}")
        g = self.group_of(slot)
        for i, p in enumerate(pages):
            assert self.refs[p] > 0, f"sharing unowned page {p}"
            assert int(p) // self.blocks_per_group == g, (
                f"page {p} belongs to group "
                f"{int(p) // self.blocks_per_group}, slot {slot} is in "
                f"group {g} — prefix sharing is group-local")
            self.tables[slot, i] = int(p)
            self.refs[p] += 1
        self.owned[slot] = len(pages)

    def page_at(self, slot: int, pos: int) -> int:
        """The page id holding cache position ``pos`` of ``slot``."""
        return int(self.tables[slot, pos // self.block_size])

    def shared_at(self, slot: int, pos: int) -> bool:
        """True when the page holding ``pos`` is shared (refs > 1) — a
        write there needs :meth:`cow` first."""
        idx = pos // self.block_size
        if idx >= int(self.owned[slot]):
            return False
        return int(self.refs[self.tables[slot, idx]]) > 1

    def cow(self, slot: int, pos: int) -> Optional[tuple]:
        """Copy-on-write: replace the shared page holding ``pos`` with a
        fresh private page (the caller device-copies the content via
        ``ops.attention.paged_copy_pages`` before any write lands).
        Returns ``(src_page, dst_page)`` — or None when the pool is dry
        (caller falls back to reclaim/overflow, nothing changed)."""
        idx = pos // self.block_size
        src = int(self.tables[slot, idx])
        assert self.refs[src] > 1, \
            f"cow on unshared page {src} (slot {slot} pos {pos})"
        g = self.group_of(slot)
        if not self._free[g]:
            return None
        dst = self._mint(g)
        self.refs[src] -= 1  # > 0 by the assert: never returns to the pool
        self.tables[slot, idx] = dst
        return src, dst

    # ----------------------------------------------- cache-side ref plumbing
    def add_ref(self, page: int) -> None:
        """One more holder of ``page`` (the PrefixCache's registration)."""
        assert self.refs[page] > 0, f"ref on unowned page {page}"
        self.refs[page] += 1

    def release_page(self, page: int) -> int:
        """Drop a non-table ref (PrefixCache eviction). Returns 1 when the
        page was physically freed."""
        return self._release(page)

    @property
    def physical_pages(self) -> int:
        """Pages currently holding data (refs > 0)."""
        return self.num_blocks - self.free_blocks


class PrefixCache:
    """Prompt-prefix → page-run cache over a :class:`BlockTables` pool.

    Keys are the literal token tuples a page's content depends on (causal
    attention: page ``i``'s k/v are a pure function of ``tokens[:cover]``
    where ``cover`` is the page's last covered position + 1), so a hit can
    never alias two different prefixes — no hash-collision risk, and the
    chain walk is one dict probe per page. Entries hold one allocator ref
    each (``BlockTables.add_ref``), so cached pages survive their creating
    request; :meth:`reclaim` drops least-recently-used chains when the
    engine needs pages back.

    Full pages register under their exact coverage key; a PARTIAL tail
    page (a prompt whose length is not a page multiple) registers under
    every prefix of its coverage too — page content at offsets < t depends
    only on ``tokens[:k*bs + t]``, so a request matching just a prefix of
    the partial page may still share it (its first own write then lands
    inside the shared page and triggers the engine's CoW). Matches are
    capped at ``len(prompt) - 1``: a request must always prefill at least
    its last prompt token to produce the logits its first sample needs.
    """

    def __init__(self, tables: BlockTables, group: Optional[int] = None):
        self.tables = tables
        # Under batch-sharded ep the engine runs ONE PrefixCache per pool
        # group (sharing is only physically possible inside a group — the
        # shards never see each other's pages); ``group`` scopes reclaim's
        # free-count check to that group's span. None = whole pool.
        self.group = group
        self.bs = tables.block_size
        # key (token tuple) -> {"page": id, "full": bool, "tick": lru}
        # partial pages appear under EVERY prefix key of their coverage;
        # all keys of one physical page share the ONE entry dict, so a
        # touch through any key refreshes the whole page's recency
        self._entries = {}
        self._tick = 0
        self.hits = 0
        self.misses = 0
        self.reclaimed_pages = 0

    def __len__(self) -> int:
        return len({id(e) for e in self._entries.values()})

    def _touch(self, entry: dict) -> None:
        self._tick += 1
        entry["tick"] = self._tick

    # --------------------------------------------------------------- match
    def match(self, tokens: list) -> tuple:
        """Longest cached prefix of ``tokens`` usable by a new request:
        ``(pages, covered)`` with ``covered <= len(tokens) - 1`` (the last
        prompt token always prefills — see class doc). Pages are returned
        in table order; the caller shares them into a slot via
        :meth:`BlockTables.share` BEFORE growing the private tail."""
        L = len(tokens)
        pages, covered = [], 0
        while covered + self.bs <= L - 1:
            e = self._entries.get(tuple(tokens[:covered + self.bs]))
            if e is None or not e["full"]:
                break
            pages.append(e["page"])
            self._touch(e)
            covered += self.bs
        # a full page whose coverage ends EXACTLY at the prompt end may
        # still be shared for its first bs-1 tokens (the last prompt token
        # re-prefills through the engine's CoW copy — identical k/v, but
        # its logits must be computed for this request's first sample)
        if covered + self.bs == L:
            e = self._entries.get(tuple(tokens[:L]))
            if e is not None and e["full"]:
                pages.append(e["page"])
                self._touch(e)
                covered += self.bs - 1
        # the partial tail: longest registered prefix of the next page
        # (an empty range when the edge above already covered L-1)
        for t in range(min(self.bs - 1, L - 1 - covered), 0, -1):
            e = self._entries.get(tuple(tokens[:covered + t]))
            if e is not None and not e["full"]:
                pages.append(e["page"])
                self._touch(e)
                covered += t
                break
        if pages:
            self.hits += 1
        else:
            self.misses += 1
        return pages, covered

    # ------------------------------------------------------------ register
    def register(self, slot: int, tokens: list) -> int:
        """Bank ``slot``'s freshly-prefilled prompt pages: one entry per
        full page plus the partial tail (under all its prefix keys).
        Already-cached keys are touched, not re-registered — a sharer's
        own table entries ARE the cached pages for the shared span, so the
        walk naturally skips them. Returns the number of NEW pages the
        cache took a ref on."""
        bt = self.tables
        L = len(tokens)
        added = 0
        for k in range(L // self.bs):
            key = tuple(tokens[:(k + 1) * self.bs])
            e = self._entries.get(key)
            if e is not None:
                self._touch(e)
                continue
            page = int(bt.tables[slot, k])
            bt.add_ref(page)
            entry = {"page": page, "full": True, "tick": 0}
            self._touch(entry)
            self._entries[key] = entry
            added += 1
        rem = L % self.bs
        if rem:
            full_key = tuple(tokens[:L])
            if full_key not in self._entries:
                page = int(bt.tables[slot, L // self.bs])
                bt.add_ref(page)
                entry = {"page": page, "full": False, "tick": 0}
                self._touch(entry)
                for t in range(1, rem + 1):
                    # prefix keys may already belong to an older entry on
                    # the same chain — first registration wins (both
                    # contents are valid for that prefix; the outer
                    # full-coverage guard means t == rem is always new)
                    key = tuple(tokens[:L - rem + t])
                    if key not in self._entries:
                        self._entries[key] = entry
                added += 1
        return added

    # ------------------------------------------------------------- reclaim
    def chains(self) -> list:
        """The MAXIMAL cached token prefixes, as token lists — the
        restart-persistence export (serve/fleet_state): a key is maximal
        when no other key extends it, so re-prefilling just these chains
        on a fresh fleet re-banks every cached page (every shorter prefix
        registers along the way). O(n²) over entry keys — the cache holds
        tens of chains, not thousands, and this runs on the persistence
        cadence, never per tick."""
        keys = list(self._entries)
        return sorted(
            (list(k) for k in keys
             if not any(len(o) > len(k) and o[:len(k)] == k
                        for o in keys)),
            key=lambda c: (len(c), c))

    def reclaim(self, n_pages: int) -> int:
        """Drop least-recently-used cached pages until ``n_pages`` are
        physically free (or the cache is empty). Evicting a page also
        evicts every longer chain that extends through it — a child whose
        parent is gone can never be matched again and would leak its ref.
        Returns the count of pages physically freed."""
        freed = 0

        def _free_now():
            if self.group is None:
                return self.tables.free_blocks
            return self.tables.free_blocks_in(self.group)

        while _free_now() < n_pages and self._entries:
            # distinct entries, oldest first
            oldest = min({id(e): e for e in self._entries.values()}.values(),
                         key=lambda e: e["tick"])
            roots = sorted((k for k, e in self._entries.items()
                            if e is oldest), key=len)
            # phase 1: a key extending any victim key is a descendant —
            # its whole ENTRY dies (an entry whose page ref is released
            # must lose every key, or a surviving shorter prefix key
            # would dangle onto a freed page)
            dead = {id(oldest): oldest}
            for key, e in self._entries.items():
                if any(len(key) >= len(r) and key[:len(r)] == r
                       for r in roots):
                    dead[id(e)] = e
            # phase 2: drop every key of every dead entry, then the refs
            self._entries = {k: e for k, e in self._entries.items()
                             if id(e) not in dead}
            for e in dead.values():
                n = self.tables.release_page(e["page"])
                freed += n
                self.reclaimed_pages += n
        return freed
