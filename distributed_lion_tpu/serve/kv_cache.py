"""Paged KV cache: a fixed page pool per layer + host-side block tables.

The vLLM PagedAttention design (Kwon et al., 2023) mapped onto the repo's
static-shape discipline: each layer's cache is ONE device array
``[num_blocks, block_size, kv_heads, head_dim]`` (the pool), and a
sequence owns an ordered list of page indices — its block table. All
allocation and free is HOST-side integer table math in this module; the
device never sees a dynamic shape, so the decode tick stays one jitted
program while sequences join and leave the batch (serve/engine.py). The
device-side scatter/gather/attend primitives live in
``ops.attention`` (``paged_scatter_kv`` / ``paged_gather_kv`` /
``paged_decode_attention``).

Sentinel convention: unallocated table entries hold ``num_blocks`` (one
past the pool). Scatters to a sentinel page drop (XLA scatter
``mode='drop'``), gathers from it fill zeros — inactive decode slots and
right-padded prefill tails are inert without a single host branch inside
the compiled tick.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def init_pages(n_layer: int, num_blocks: int, block_size: int,
               kv_heads: int, head_dim: int, dtype) -> list:
    """The per-layer device page pool: ``[{"k", "v"}] * n_layer`` of
    zeros ``[num_blocks, block_size, kv_heads, head_dim]``. Allocated
    once at engine start — ticks update it in place (donated)."""
    import jax.numpy as jnp

    shape = (num_blocks, block_size, kv_heads, head_dim)
    return [
        {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        for _ in range(n_layer)
    ]


def bucket_tokens(n: int, block_size: int, max_blocks_per_seq: int) -> int:
    """Padded prefill length for an ``n``-token prompt: power-of-two
    pages, so prompt-length variety costs O(log(max)) compiles, not one
    per length. The ONE bucketing rule — the serving engine's prefill and
    the draft-model mirror's prefill (serve/speculate.py) must pad
    identically or the mirror desyncs."""
    blocks = 1
    while blocks * block_size < n:
        blocks *= 2
    return min(blocks, max_blocks_per_seq) * block_size


class BlockTables:
    """Host-side page allocator + per-slot block tables.

    ``tables`` is the ``[max_seqs, max_blocks_per_seq]`` int32 array the
    engine ships to the device each tick (sentinel-padded); ``owned[slot]``
    counts the pages slot currently holds. Pure numpy/stdlib — this is
    the "allocation is host-side table math, never a recompile" half of
    the paged design, and it must stay importable without jax for the
    bench's capacity planning.
    """

    def __init__(self, num_blocks: int, block_size: int, max_seqs: int,
                 max_blocks_per_seq: int):
        if num_blocks < 1 or block_size < 1:
            raise ValueError(
                f"need positive pool dims, got num_blocks={num_blocks} "
                f"block_size={block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_seqs = int(max_seqs)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.sentinel = self.num_blocks
        # LIFO free list: recently-freed pages are re-used first, which
        # keeps the working set of the pool small and cache-warm
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self.tables = np.full((max_seqs, max_blocks_per_seq), self.sentinel,
                              np.int32)
        self.owned = np.zeros((max_seqs,), np.int32)

    # ------------------------------------------------------------ capacity
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def max_tokens_per_seq(self) -> int:
        return self.max_blocks_per_seq * self.block_size

    def blocks_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` cache entries."""
        return -(-max(n_tokens, 0) // self.block_size)

    def can_grow(self, slot: int, n_tokens: int) -> bool:
        """Would :meth:`grow` succeed for ``n_tokens`` total tokens?"""
        need = self.blocks_for(n_tokens)
        if need > self.max_blocks_per_seq:
            return False
        return need - int(self.owned[slot]) <= len(self._free)

    # ---------------------------------------------------------- alloc/free
    def grow(self, slot: int, n_tokens: int) -> bool:
        """Ensure ``slot``'s table covers ``n_tokens`` total cache
        entries, allocating pages as needed. Returns False (allocating
        NOTHING — all-or-nothing, so a half-grown slot can't strand
        pages) when the pool or the table width can't fit it."""
        if not self.can_grow(slot, n_tokens):
            return False
        need = self.blocks_for(n_tokens)
        have = int(self.owned[slot])
        for i in range(have, need):
            self.tables[slot, i] = self._free.pop()
        self.owned[slot] = need
        return True

    def shrink(self, slot: int, n_tokens: int) -> int:
        """Free ``slot``'s pages beyond those ``n_tokens`` total cache
        entries need — the EXACT inverse of :meth:`grow`: pages return to
        the LIFO free list in reverse allocation order, so
        ``grow(slot, a); shrink(slot, b)`` leaves the allocator (tables,
        owned, free-list order) bit-identical to ``grow(slot, b)`` for any
        ``b <= a``. This is the speculative-decode rollback primitive
        (serve/speculate.py): a verify window optimistically grows the
        table for k draft tokens and the rejected tail's pages are handed
        back as if they were never allocated, so the post-commit state
        matches what a token-by-token run would hold (tests/test_serve.py
        pins it). Returns the page count freed."""
        need = self.blocks_for(n_tokens)
        have = int(self.owned[slot])
        if need >= have:
            return 0
        for i in range(have - 1, need - 1, -1):
            self._free.append(int(self.tables[slot, i]))
            self.tables[slot, i] = self.sentinel
        self.owned[slot] = need
        return have - need

    def free_slot(self, slot: int) -> int:
        """Return all of ``slot``'s pages to the pool; the table row goes
        back to sentinel (inert on device). Returns the page count freed."""
        n = int(self.owned[slot])
        for i in range(n):
            self._free.append(int(self.tables[slot, i]))
        self.tables[slot, :] = self.sentinel
        self.owned[slot] = 0
        return n

    def find_free_slot(self) -> Optional[int]:
        """Lowest slot index owning zero pages (the engine marks a slot
        occupied by growing it; completed slots are freed)."""
        for s in range(self.max_seqs):
            if self.owned[s] == 0:
                return s
        return None
