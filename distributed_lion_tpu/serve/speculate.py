"""Speculative decode on the paged KV cache: draft / verify / commit.

ROADMAP item 3 (Leviathan et al., "Fast Inference from Transformers via
Speculative Decoding", 2023): at batch 32-256 the decode tick is
memory-bandwidth-bound on weights it reads once per token, so a cheap
drafter proposes k tokens per slot, ONE batched verify dispatch scores all
of them against the target model, and the accepted prefix commits to the
block tables — the classic 2-3x decode lever, built so the repo's
bit-identity discipline survives intact.

**The acceptance rule is the pinned PRNG stream itself.** The serving
engine already draws every token of request r from
``fold_in(key(r.seed), token_index)`` (serve/engine._sample_rows) — a
stream that depends only on the request, never on batching. The verify
dispatch therefore computes, for every window position, the EXACT token
the non-speculative engine would have produced there (argmax when greedy;
the per-index categorical draw when sampling) and accepts a draft token
iff it equals that pinned draw. The committed tokens ARE the
non-speculative run's tokens by construction — greedy speculative output
is bit-identical to non-speculative paged decode and sampled output is
token-identical to the same per-request stream (tests/test_speculate.py
pins both, across both drafters x k in {2,4}) — and the drafter only ever
changes HOW FAST the stream is emitted, never what it says. (Classic
p/q rejection sampling preserves the output *distribution*; replaying the
pinned stream preserves the output *sequence*, which is the stronger
guarantee this repo's evidence artifacts are built on.)

One speculative tick (replaces the engine's decode tick when
``ServeConfig.speculate`` is set):

- **draft** — the drafter proposes up to k tokens per active slot
  (``serve/draft`` span). Host-side n-gram drafting is pure table math;
  the draft-model drafter is ONE jitted scan dispatch (its per-token
  draws never touch the host — graft-check DLT001 pins the forbidden
  shape, tests/fixtures/analysis/serve/dlt001_verify_host_read.py).
- **verify** — ONE jitted dispatch scores the whole batch's windows
  ``[last_tok, d_1 .. d_k]`` ([B, k+1] with per-row valid counts) against
  the target on the paged cache: speculative k/v land in the already-owned
  or freshly-grown pages (``ops.attention.paged_scatter_kv`` masks the
  invalid tail), attention is causal inside the window, and all k+1 pinned
  draws come back as ONE [B, k+1] array — one host sync per tick, exactly
  like the non-speculative engine (``serve/verify`` span).
- **commit** — per slot: accept the longest draft prefix matching the
  pinned draws, append ``accepted + 1`` tokens (the first mismatch
  position yields the CORRECTED token; a full match yields the bonus
  draw), and roll the block table back over the rejected tail with
  ``BlockTables.shrink`` — the exact inverse of the optimistic grow, so
  len/last/table/free-list state after a partial accept equals what a
  token-by-token run would hold (``serve/commit`` span).

Drafters (one :class:`Drafter` protocol):

- ``ngram:<k>`` — host-side self-drafting suffix-cache lookup (prompt
  lookup decoding): propose the k tokens that followed the most recent
  earlier occurrence of the sequence's own suffix. Zero extra device
  memory or dispatches; great on repetitive / system-prompt traffic,
  proposes nothing (v=0, plain decode) when the history has no signal.
- ``draft:<k>`` — a tiny draft model (its own :class:`ServeModel` with
  its own page pool and block tables, same geometry as the target's)
  greedily proposes k tokens in one scan dispatch. The draft cache mirrors
  the target's committed history exactly: accepted drafts' k/v were
  written during drafting, the corrected/bonus token is ingested as the
  first scan step of the NEXT round, and the rejected tail rolls back
  with the same ``shrink`` math.

MoE checkpoints (ISSUE 15): ``ngram:<k>`` composes — the verify window is
just a wider decode dispatch, MoE inference routing is no-drop per-token
with draft lanes valid-masked (models/gpt2._decode_mlp), and rollback
over MoE pages is attention-side only, so speculative == plain holds
unchanged (tests/test_moe_serve.py pins it). ``draft:<k>`` keeps a loud
refusal: the draft mirror holds its OWN page pool and block tables, and
an expert-parallel target would leave that mirror pool unsharded on the
mesh — the mirror-pool residual (ROADMAP item 3/4) has no honest sharded
budget yet.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from distributed_lion_tpu.serve.kv_cache import BlockTables, init_pages
from distributed_lion_tpu.train import journal


def parse_speculate(spec: str) -> Tuple[str, int]:
    """``"<drafter>:<k>"`` → ``(drafter, k)`` with loud validation — the
    one grammar shared by ServeConfig.speculate, cli/run_serve and
    scripts/bench_serve.py."""
    name, _, ks = spec.partition(":")
    if name not in ("ngram", "draft"):
        raise ValueError(
            f"unknown drafter {name!r} in --speculate {spec!r} "
            "(ngram:<k> | draft:<k>)")
    try:
        k = int(ks)
    except ValueError:
        raise ValueError(
            f"--speculate {spec!r} needs an integer draft length "
            "(e.g. ngram:4)") from None
    if not 1 <= k <= 16:
        raise ValueError(f"--speculate draft length must be in [1, 16], "
                         f"got {k}")
    return name, k


def ngram_propose(seq: List[int], k: int, max_n: int = 3) -> List[int]:
    """Suffix-cache proposal: find the most recent EARLIER occurrence of
    the sequence's longest suffix (n down from ``max_n``) and return up to
    ``k`` of the tokens that followed it. [] = no signal (the caller runs
    a plain decode for that slot). Pure list math — the host-side half of
    prompt-lookup decoding."""
    L = len(seq)
    if k <= 0 or L < 2:
        return []
    for n in range(min(max_n, L - 1), 0, -1):
        pat = seq[L - n:]
        for j in range(L - n - 1, -1, -1):
            if seq[j:j + n] == pat:
                # j + n <= L - 1, so the continuation always has at
                # least seq[j + n] — a match never comes back empty
                return [int(t) for t in seq[j + n:j + n + k]]
    return []


class NGramDrafter:
    """Self-drafting from the request's own token history (prompt + the
    generated stream) — no device state, no extra dispatches.

    The suffix index is INCREMENTAL: each appended token records the
    n-grams it completes (n ≤ max_n) with their two most recent start
    positions, so a propose is max_n dict probes instead of the reference
    scan's full-history walk (O(L) per tick → O(L²) per request — the
    review-flagged shape; :func:`ngram_propose` stays as the reference
    the index is fuzz-pinned against). The current suffix is always its
    own most recent indexed occurrence, so the SECOND-most-recent start
    is exactly the "most recent earlier occurrence" the reference finds.
    Histories sync lazily from the slot's ``gen`` at propose time via a
    consumed-count cursor — no assumptions about which engine path
    (prefill first-token, speculative commit) appended the tokens."""

    name = "ngram"

    def __init__(self, k: int, max_n: int = 3):
        self.k = int(k)
        self.max_n = int(max_n)
        self._hist = {}   # slot -> [token, ...] == req.tokens + gen
        self._index = {}  # slot -> {ngram: (latest_start, prev_start)}
        self._ngen = {}   # slot -> how many of gen are already indexed

    def _append(self, slot: int, tokens) -> None:
        hist, index = self._hist[slot], self._index[slot]
        for t in tokens:
            hist.append(int(t))
            p = len(hist) - 1
            for n in range(1, min(self.max_n, p + 1) + 1):
                gram = tuple(hist[p - n + 1:p + 1])
                prev = index.get(gram)
                index[gram] = (p - n + 1, None if prev is None else prev[0])

    def admit(self, slot: int, tokens: List[int],
              n_committed: int = 0) -> None:
        # ``tokens`` is the slot's full prefilled history; its last
        # ``n_committed`` entries are ALSO the head of the slot's ``gen``
        # (a migrated request resumes mid-stream, serve/replica_plane) —
        # start the gen cursor past them or the propose-time sync would
        # index the committed tokens twice
        self._hist[slot] = []
        self._index[slot] = {}
        self._ngen[slot] = int(n_committed)
        self._append(slot, tokens)

    def evict(self, slot: int) -> None:
        self._hist.pop(slot, None)
        self._index.pop(slot, None)
        self._ngen.pop(slot, None)

    def commit(self, slot: int, cache_len: int) -> None:
        pass  # propose syncs from gen itself — nothing extra to do here

    def _lookup(self, slot: int, k: int) -> List[int]:
        hist, index = self._hist[slot], self._index[slot]
        L = len(hist)
        if k <= 0 or L < 2:
            return []
        for n in range(min(self.max_n, L - 1), 0, -1):
            # the suffix indexed itself when its last token appended, so
            # entry[0] == L - n; entry[1] is the most recent EARLIER start
            j = index[tuple(hist[L - n:])][1]
            if j is not None:
                return hist[j + n:j + n + k]
        return []

    def propose(self, active: List[int], slots, desired: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray]:
        drafts = np.zeros((len(slots), self.k), np.int32)
        counts = np.zeros((len(slots),), np.int32)
        for i in active:
            s = slots[i]
            new = s.gen[self._ngen[i]:]
            if new:
                self._append(i, new)
                self._ngen[i] = len(s.gen)
            if len(self._hist[i]) != len(s.req.tokens) + len(s.gen):
                raise RuntimeError(
                    f"ngram history desynced on slot {i}: index holds "
                    f"{len(self._hist[i])} tokens, slot "
                    f"{len(s.req.tokens) + len(s.gen)} — a drafter "
                    "bookkeeping bug")
            cont = self._lookup(i, int(desired[i]))
            counts[i] = len(cont)
            drafts[i, :len(cont)] = cont
        return drafts, counts


class DraftModelDrafter:
    """A small draft model proposing greedily on its OWN paged cache.

    The draft cache mirrors the target's committed history position for
    position (``self.len[slot] == slot.cache_len`` at every tick start):
    one scan dispatch per round ingests the newest committed token
    (``last_tok``) and drafts k more, writing their k/v as it goes, so an
    accepted draft's cache entry is already in place and a rejected tail
    rolls back with the same :meth:`BlockTables.shrink` math as the
    target. A slot whose draft pool can't fit even the ingest goes
    draft-dead (plain decode, counted in ``draft_dead``) rather than
    corrupting the mirror — loud in stats, silent in outputs."""

    name = "draft"

    def __init__(self, model, k: int, cfg):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.k = int(k)
        self.cfg = cfg
        nb = cfg.resolved_num_blocks()
        horizon = cfg.block_size * cfg.max_blocks_per_seq
        if model.max_positions is not None and horizon > model.max_positions:
            raise ValueError(
                f"draft model's position budget {model.max_positions} is "
                f"smaller than the page horizon {horizon}; a draft window "
                "past it would silently alias — use a draft model trained "
                "to at least the serving horizon")
        self.tables = BlockTables(nb, cfg.block_size, cfg.max_seqs,
                                  cfg.max_blocks_per_seq)
        self.pages = init_pages(model.n_layer, nb, cfg.block_size,
                                model.kv_heads, model.head_dim,
                                model.cache_dtype)
        self.len = np.zeros((cfg.max_seqs,), np.int32)
        self.dead = np.zeros((cfg.max_seqs,), bool)
        self.draft_dead = 0
        donate = (1,) if jax.default_backend() != "cpu" else ()

        def prefill(params, pages, tables, toks, length):
            valid = jnp.arange(toks.shape[1])[None, :] < length
            _, pages = model.decode_paged(params, toks, pages, tables,
                                          jnp.zeros((1,), jnp.int32), valid)
            return pages

        def draft(params, pages, tables, lens, last, dcount):
            # scan step i ingests window token i (i=0: last_tok, i>=1: the
            # (i)th draft) at position lens+i and emits the NEXT greedy
            # token; rows write only steps 0..dcount[row] (masked beyond),
            # draft-dead rows (dcount=-1) write nothing. The final step's
            # emitted token is discarded — it only exists to write d_k's
            # k/v so a fully-accepted round leaves the mirror complete.
            def body(carry, i):
                tok, pos, pages = carry
                valid = (i <= dcount)[:, None]
                logits, pages = model.decode_paged(params, tok[:, None],
                                                   pages, tables, pos, valid)
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(tok.dtype)
                return (nxt, pos + 1, pages), nxt

            (_, _, pages), toks = jax.lax.scan(
                body, (last, lens, pages),
                jnp.arange(self.k + 1, dtype=lens.dtype))
            return toks[: self.k].T, pages  # [B, k] proposals

        self._prefill = jax.jit(prefill, donate_argnums=donate)
        self._draft = jax.jit(draft, donate_argnums=donate)
        # pre-jit bodies + donation, kept for the engine's dispatch
        # registry (build_speculator registers the mirror's dispatches so
        # analysis/serve_check and compile_counts() see EVERY serve
        # dispatch, the draft mirror's included)
        self._prefill_fn, self._draft_fn = prefill, draft
        self._donate = donate

    def _bucket(self, n: int) -> int:
        # the engine's exact bucketing rule — the mirror must pad like
        # the target or the two prefills land k/v at different positions
        from distributed_lion_tpu.serve.kv_cache import bucket_tokens

        return bucket_tokens(n, self.cfg.block_size,
                             self.cfg.max_blocks_per_seq)

    def _go_dead(self, slot: int) -> None:
        # a dead slot decodes plain until evicted — hand its mirror pages
        # back NOW, or under a tight draft pool one dead slot's stranded
        # history cascades every other slot into draft-dead too
        self.dead[slot] = True
        self.draft_dead += 1
        self.tables.free_slot(slot)
        self.len[slot] = 0

    def admit(self, slot: int, tokens: List[int],
              n_committed: int = 0) -> None:
        # the mirror prefills the slot's FULL history (a migrated
        # request's committed tokens included — they are cache content
        # like any other); n_committed only matters to gen-cursor
        # drafters, so it is accepted and unused here
        import jax.numpy as jnp

        L = len(tokens)
        if not self.tables.grow(slot, L):
            self._go_dead(slot)
            return
        P = self._bucket(L)
        toks = np.zeros((1, P), np.int32)
        toks[0, :L] = tokens
        self.pages = self._prefill(
            self.model.params, self.pages,
            jnp.asarray(self.tables.tables[slot:slot + 1]),
            jnp.asarray(toks), jnp.int32(L))
        self.len[slot] = L
        self.dead[slot] = False

    def evict(self, slot: int) -> None:
        self.tables.free_slot(slot)
        self.len[slot] = 0
        self.dead[slot] = False

    def commit(self, slot: int, cache_len: int) -> None:
        if self.dead[slot]:
            return
        # accepted drafts' k/v were written during drafting; the rejected
        # tail rolls back exactly like the target's
        self.len[slot] = cache_len
        self.tables.shrink(slot, cache_len)

    def propose(self, active: List[int], slots, desired: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray]:
        import jax.numpy as jnp

        S = len(slots)
        dcount = np.full((S,), -1, np.int32)
        lens = np.zeros((S,), np.int32)
        last = np.zeros((S,), np.int32)
        for i in active:
            if self.dead[i]:
                continue
            if int(self.len[i]) != int(slots[i].cache_len):
                raise RuntimeError(
                    f"draft cache desynced on slot {i}: draft holds "
                    f"{int(self.len[i])} positions, target "
                    f"{int(slots[i].cache_len)} — a drafter bookkeeping bug")
            d = int(desired[i])
            while d >= 0 and not self.tables.grow(
                    i, int(self.len[i]) + d + 1):
                d -= 1
            if d < 0:
                self._go_dead(i)
                continue
            dcount[i] = d
            lens[i] = self.len[i]
            last[i] = slots[i].last_tok
        drafts, self.pages = self._draft(
            self.model.params, self.pages, jnp.asarray(self.tables.tables),
            jnp.asarray(lens), jnp.asarray(last), jnp.asarray(dcount))
        drafts = np.asarray(drafts)  # ONE host sync per draft dispatch
        return drafts, np.maximum(dcount, 0)


class Speculator:
    """The engine-side driver: owns the drafter and the jitted verify
    dispatch, and runs the speculative decode tick in place of the
    engine's one-token tick (serve/engine.ServingEngine._decode)."""

    def __init__(self, engine, drafter, k: int):
        import jax
        import jax.numpy as jnp

        from distributed_lion_tpu.serve.engine import _sample_rows

        self.engine = engine
        self.drafter = drafter
        self.k = int(k)
        for key in ("spec_rounds", "spec_proposed", "spec_accepted"):
            engine.stats.setdefault(key, 0)
        samp = (engine.cfg.temperature, engine.cfg.top_k, engine.cfg.top_p)
        model = engine.model
        # the engine's resolved mesh axes (None off-mesh): an ep-only mesh
        # must NOT bind the tensor axis here — the verify window shards
        # exactly like the engine's own decode tick
        tp_axis, ep_axis = engine._tp_axis, engine._ep_axis

        moe_stats = engine._moe_stats
        stats_axis = ep_axis if engine._ep_batch else None

        def verify(params, pages, tables, lens, window, vcounts, seeds,
                   counts):
            # window [B, k+1] = [last_tok, d_1 .. d_k]; row b's first
            # vcounts[b] entries are real (0 = inactive slot: every write
            # drops, the draws are garbage the host never reads). Under
            # batch-sharded ep every operand is this shard's local slot
            # slice, tables carry group-local page ids.
            W = window.shape[1]
            valid = jnp.arange(W)[None, :] < vcounts[:, None]
            out = model.decode_paged(params, window, pages, tables, lens,
                                     valid, tp_axis=tp_axis,
                                     ep_axis=ep_axis,
                                     return_moe_stats=moe_stats,
                                     stats_axis=stats_axis)
            logits, pages = out[0], out[1]
            st = out[2] if moe_stats else {}
            B, _, V = logits.shape
            # the pinned per-request stream: position s of row b draws
            # with fold_in(key(seed_b), counts_b + s) — exactly the key
            # the non-speculative tick would use for that token index
            seeds_r = jnp.repeat(seeds, W)
            counts_r = (counts[:, None]
                        + jnp.arange(W, dtype=counts.dtype)[None, :])
            draws = _sample_rows(logits.reshape(B * W, V), seeds_r,
                                 counts_r.reshape(-1), *samp)
            return (draws.reshape(B, W), st), pages

        # the engine's dispatch wrapper: plain jit at tp=0, shard_map'd
        # over the serving mesh under TP (ISSUE 13) — the verify window
        # is just a wider decode tick, so it shards identically; under
        # batch-sharded ep (ISSUE 16) every slot-leading operand and the
        # [B, k+1] draws shard over the expert axis like the decode tick
        if engine._ep_batch:
            from jax.sharding import PartitionSpec as P

            from distributed_lion_tpu.parallel.mesh import EXPERT_AXIS

            bsp, rep = P(EXPERT_AXIS), P()
            self._verify = engine._jit_paged(
                verify, n_rest=6,
                rest_specs=(P(EXPERT_AXIS, None), bsp, bsp, bsp, bsp, bsp),
                out_spec=(bsp, rep), name="verify")
        else:
            self._verify = engine._jit_paged(verify, n_rest=6,
                                             name="verify")

    # lifecycle relays from the engine
    def on_admit(self, slot: int, tokens: List[int],
                 n_committed: int = 0) -> None:
        self.drafter.admit(slot, tokens, n_committed)

    def on_evict(self, slot: int) -> None:
        self.drafter.evict(slot)

    def decode_tick(self, completions: List) -> None:
        import jax.numpy as jnp

        eng = self.engine
        tables = eng.tables
        active = [i for i, s in enumerate(eng.slots) if s is not None]
        if not active:
            return
        S = eng.cfg.max_seqs
        jrnl = journal.active()

        # two-phase grow. Phase 1 reserves every active slot's ONE
        # mandatory write (last_tok) first — the exact loop the plain
        # tick runs — so WITHIN a tick drafting never costs a LATER slot
        # its mandatory page because an earlier slot optimistically took
        # k extra (the single-phase grow had that bug; regression-pinned
        # on a symmetric workload). ACROSS ticks no such pin is possible:
        # speculation advances high-accept slots more tokens per tick, so
        # when the pool exhausts under an ASYMMETRIC workload the
        # overflow eviction can land on a different request than plain —
        # a race against exhaustion whose racers changed speed, not
        # words. The unconditional invariant (pinned): each request's
        # output is a prefix of the other run's, completed requests
        # identical.
        cow_pairs = []
        for i in list(active):
            s = eng.slots[i]
            if not (eng._grow(i, s.cache_len + 1)
                    and eng._cow_if_shared(i, s.cache_len, cow_pairs)):
                eng._maybe_finish(i, completions, overflow=True)
                active.remove(i)
        if not active:
            return
        # Phase 2: drafts claim only the LEFTOVER pool — the token budget
        # caps the window (a slot one token from its budget needs no
        # drafts), then degrade to fewer drafts as grows fail; rejected
        # tails hand their pages back at commit. Only the FIRST write
        # position can sit in a shared page (pages past the prompt are
        # always private), so phase 1's CoW covers the whole window.
        desired = np.zeros((S,), np.int32)
        for i in active:
            s = eng.slots[i]
            v = max(min(self.k, s.budget - len(s.gen) - 1), 0)
            # plain tables.grow, NOT eng._grow: a draft page is optional
            # and rolls back at commit — it must degrade to fewer drafts
            # under pressure, never evict prefix-cache chains to exist
            while v > 0 and not tables.grow(i, s.cache_len + v + 1):
                v -= 1
            desired[i] = v
        eng._flush_cow(cow_pairs)

        with jrnl.span("serve/draft", drafter=self.drafter.name,
                       batch=len(active), k=self.k):
            drafts, counts = self.drafter.propose(active, eng.slots, desired)

        window = np.zeros((S, self.k + 1), np.int32)
        vcounts = np.zeros((S,), np.int32)
        lens = np.zeros((S,), np.int32)
        seeds = np.zeros((S,), np.uint32)
        gcounts = np.zeros((S,), np.int32)
        for i in active:
            s = eng.slots[i]
            v = int(min(desired[i], counts[i]))
            desired[i] = v
            window[i, 0] = s.last_tok
            if v:
                window[i, 1:1 + v] = drafts[i, :v]
            vcounts[i] = v + 1
            lens[i] = s.cache_len
            seeds[i] = s.req.seed
            gcounts[i] = len(s.gen)

        with jrnl.span("serve/verify", batch=len(active),
                       proposed=int(sum(desired[i] for i in active))):
            rest = (eng._device_tables(), jnp.asarray(lens),
                    jnp.asarray(window), jnp.asarray(vcounts),
                    jnp.asarray(seeds), jnp.asarray(gcounts))
            eng._guard("verify", rest)
            (draws, st), eng.pages = self._verify(
                eng.params, eng.pages, *rest)
            draws = np.asarray(draws)  # ONE host sync for the whole batch
            eng._absorb_moe_stats(st)

        accepted_total = committed_total = 0
        with jrnl.span("serve/commit", batch=len(active)) as commit_span:
            for i in active:
                s = eng.slots[i]
                v = int(desired[i])
                m = 0
                while m < v and draws[i, m] == window[i, m + 1]:
                    m += 1
                eng.stats["spec_proposed"] += v
                eng.stats["spec_accepted"] += m
                accepted_total += m
                # commit draws[0..m] one at a time with the plain tick's
                # finish rules — EOS inside the accepted prefix truncates
                # there, exactly where the token-by-token run would stop
                finished = False
                n_taken = 0
                for t in (int(t) for t in draws[i, :m + 1]):
                    s.gen.append(t)
                    n_taken += 1
                    if (eng.cfg.eos_id is not None
                            and t == eng.cfg.eos_id) \
                            or len(s.gen) >= s.budget:
                        finished = True
                        break
                s.cache_len += n_taken
                s.last_tok = s.gen[-1]
                eng.stats["decode_tokens"] += n_taken
                committed_total += n_taken
                if finished:
                    eng._maybe_finish(i, completions)
                    continue
                # roll the rejected tail's pages back: post-commit state
                # == the state a token-by-token run would hold
                tables.shrink(i, s.cache_len)
                self.drafter.commit(i, s.cache_len)
            commit_span.set(accepted=accepted_total,
                            committed=committed_total)
        eng.stats["decode_ticks"] += 1
        eng.stats["spec_rounds"] += 1


def build_speculator(engine, spec: str,
                     draft_model: Optional[object] = None) -> Speculator:
    """Construct the Speculator for ``ServeConfig.speculate`` — called by
    ServingEngine at build. ``draft_model`` (a ServeModel) is required for
    ``draft:<k>`` and must share the target's vocabulary."""
    name, k = parse_speculate(spec)
    if name == "ngram":
        drafter = NGramDrafter(k)
    else:
        if getattr(engine.model.cfg, "moe_experts", 0) > 0 or (
                draft_model is not None
                and getattr(draft_model.cfg, "moe_experts", 0) > 0):
            raise ValueError(
                "--speculate draft:<k> does not support MoE checkpoints "
                "yet: the draft MIRROR keeps its own page pool and block "
                "tables, and that mirror pool has no sharded budget under "
                "expert parallelism — the mirror-pool residual (ROADMAP "
                "items 3/4); use ngram:<k> (pinned speculative==plain for "
                "MoE) or serve without speculation")
        if engine._mesh is not None:
            raise ValueError(
                "--speculate draft:<k> does not compose with --serve_tp "
                "yet: the draft mirror would keep its own unsharded page "
                "pool on rank 0 and steal page-pool HBM from the sharded "
                "target (ROADMAP item 3 residual); use ngram:<k> — the "
                "host-side drafter needs no device state — or serve "
                "without TP")
        if draft_model is None:
            raise ValueError(
                "--speculate draft:<k> needs a draft model "
                "(ServingEngine(draft_model=...) / cli --draft_model_path)")
        tv = getattr(engine.model.cfg, "vocab_size", None)
        dv = getattr(draft_model.cfg, "vocab_size", None)
        if tv != dv:
            raise ValueError(
                f"draft model vocab {dv} != target vocab {tv}; the drafted "
                "token ids would be meaningless to the target")
        drafter = DraftModelDrafter(draft_model, k, engine.cfg)
        engine._register_dispatch("draft_prefill", drafter._prefill,
                                  drafter._prefill_fn, drafter._donate,
                                  None, None)
        engine._register_dispatch("draft_step", drafter._draft,
                                  drafter._draft_fn, drafter._donate,
                                  None, None)
    return Speculator(engine, drafter, k)
