"""Continuous-batching inference engine over the paged KV cache.

The serving counterpart of ``train/loop.py`` (ROADMAP item 4): requests
join a rolling batch on arrival, leave on EOS/length/overflow, and every
tick is ONE device dispatch — either a bucketed prefill or a decode step
over all active slots. The host's only per-tick work is table math
(serve/kv_cache.py) and reading back the tick's sampled tokens as one
array; there is no per-token host sync inside a tick (graft-check DLT001
pins the forbidden shape, tests/fixtures/analysis/serve/).

Scheduling (the vLLM recipe, simplified to two tick kinds):

- **admit** — pending requests take a free slot while pages fit, subject
  to a fairness cap on prefill tokens per engine tick
  (``prefill_cap_tokens``): a burst of long prompts cannot starve the
  decode batch for more than one tick.
- **prefill** — one dispatch per admitted request at a power-of-two
  bucketed length (a handful of compiles total, never per-prompt), tail
  masked via the scatter's ``valid`` lanes; samples the request's first
  token inside the same dispatch.
- **decode tick** — one dispatch advancing EVERY active slot one token:
  block-table decode (``*_decode_paged``) + per-slot sampling. Per-slot
  PRNG keys are ``fold_in(key(request.seed), generated_index)`` — a
  request's sample stream depends only on the request, NOT on which slot
  it rides or who shares the batch, which is what makes a staggered
  continuous-batching run produce outputs identical to solo runs
  (tests/test_serve.py pins it).
- **evict** — EOS / ``max_new_tokens`` / cache-overflow slots release
  their page refs; the block table row goes back to sentinel, so the next
  decode tick simply ignores the slot (no recompile, the shapes never
  changed).

**Tensor-parallel serving** (``ServeConfig.tp`` — ISSUE 13): the engine
composes with ``parallel/tensor_parallel`` exactly the way the trainer
does — attention/MLP weights sharded per the Megatron param specs, the
page pools sharded over their KV-HEAD axis across a ``(data=1,
tensor=tp)`` mesh, and every decode/prefill/verify dispatch shard_map'd
over the slice. The kv-head axis is embarrassingly parallel through the
whole paged chain (scatter/gather/attend are per-head), so each rank runs
the same program on its head shard and only the row-parallel output
projections cross the tensor axis (one psum per block). Host-side block
tables stay REPLICATED numpy — allocation is the same table math at any
tp and never recompiles. ``tp=0`` (default) is the single-device path,
bit-for-bit the pre-TP engine; ``tp=1`` runs the sharded program on a
1-mesh and is pinned bit-identical to it; ``tp>1`` divides weight + KV
HBM per chip and is pinned token-identical on CPU mesh emulation
(tests/test_tp_serve.py).

**Expert-parallel MoE serving** (``ServeConfig.ep`` — ISSUE 15, the PR 9
refusals lifted): MoE checkpoints serve through the paged engine. Pad and
sentinel lanes carry a ``valid`` mask into expert routing
(parallel/expert.moe_ffn) so they consume zero expert capacity, and
inference routing is NO-DROP (models/gpt2._decode_mlp) — an exact
per-token function, which is what makes paged MoE decode bit-identical to
the dense-KV MoE path, batched identical to solo, and the prefix-cache /
n-gram-speculation compositions hold unchanged. ``ep >= 1`` shards the
expert FFN banks over the expert axis of a ``(data=1, expert=ep,
tensor=max(tp,1))`` mesh via the SAME ``moe_param_specs`` trees the
trainer uses — two ``all_to_all`` hops per MoE block per tick, page pools
untouched (attention stays shard-local exactly as TP left it). NF4/int8
expert banks shard with the dense specs. ``ep=1`` is pinned bit-identical
to the unsharded program; ``ep in {2,4}`` and ep×tp are pinned
token-identical on CPU mesh emulation (tests/test_moe_serve.py).
``draft:<k>`` speculation keeps its loud MoE refusal (the mirror-pool
residual, serve/speculate.py).

**Prefix sharing** (``ServeConfig.prefix_cache``): a prompt-prefix →
page-run cache with per-page refcounts (serve/kv_cache.PrefixCache). An
admitted request shares the cached pages covering its prompt prefix (one
physical copy for N requests carrying the same system prompt), prefills
only the uncovered suffix (the shared pages already hold its k/v —
computed once, by the first request, from the same tokens and weights,
hence bit-identical), and copy-on-write kicks in at the first divergent
write: a write landing in a ref>1 page first copies that page
(``ops.attention.paged_copy_pages``) so ``paged_scatter_kv`` targets a
private clone for the written suffix only. ``grow``/``shrink``/free are
refcount ops — speculative rollback over a shared table row releases
refs without freeing pages a neighbor still reads. Outputs are pinned
identical to the unshared engine (greedy, sampled, and speculative —
tests/test_serve.py / test_speculate.py).

With ``ServeConfig.speculate`` set, the decode tick is replaced by the
speculative draft/verify/commit round (serve/speculate.py): up to k
drafted tokens per slot ride ONE batched verify dispatch and the accepted
prefix commits to the block tables — outputs pinned identical to this
one-token tick (greedy bit-identical, sampled token-identical to the same
per-request stream), only the tokens-per-dispatch ratio changes.

NF4/int8 frozen-weight serving: ``quant='nf4'`` re-packs the dense
checkpoint through ``ops.quant.quantize_tree`` once at engine build; the
decode paths dequantize inside each matmul's producer fusion
(``maybe_dequant``), so a 7B checkpoint serves from ~0.5 byte/param of
HBM plus the page pool. Under TP the quantized leaves shard with the SAME
specs as their dense twins (the shaped layout's last-dim blocks never
straddle a shard boundary — ops/quant.validate_quant_tp fails fast when a
block size can't split).

**Elastic serving** (ISSUE 14): every unfinished request is exportable as
a :class:`RecoveryRecord` — prompt + committed tokens + seed (+ budget and
deadline) — and a request carrying ``committed`` tokens re-admits by
prefilling its whole history and RESUMING the pinned per-request sample
stream at ``token_index = len(committed)``. Because every draw's key is
``fold_in(key(seed), token_index)`` and prefill-computed k/v are
bit-identical to decode-written k/v for the same tokens at the same
positions, a migrated request's continued stream is token-identical to
the uninterrupted one by construction — the property
``serve/replica_plane.ServingFleet`` builds replica crash/drain/rejoin on
(tests/test_replica_plane.py pins it, greedy/sampled/speculative,
prefix_cache on and off). Requests may also carry a wall-clock
``deadline_s``; expiry evicts with the honest ``timeout`` status at the
next tick boundary, partial output attached.

Journal spans (``serve/admit``, ``serve/prefill``, ``serve/decode_tick``,
``serve/cow``, ``serve/evict``) ride the PR-7 run journal when one is
installed (train/journal.install), giving ``cli/run_analyze`` a per-tick
timeline.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from distributed_lion_tpu.parallel.mesh import EXPERT_AXIS, TENSOR_AXIS
from distributed_lion_tpu.serve.kv_cache import (
    BlockTables,
    PrefixCache,
    bucket_tokens,
    init_pages,
)
from distributed_lion_tpu.serve.metrics import RequestTimes, ServeMetrics
from distributed_lion_tpu.train import journal


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    max_seqs: int = 8            # rolling-batch width (decode slots)
    block_size: int = 16         # tokens per KV page
    max_blocks_per_seq: int = 8  # block-table width; per-seq cap =
    #                              block_size * max_blocks_per_seq tokens
    num_blocks: int = 0          # page-pool size; 0 = auto
    #                              (max_seqs * max_blocks_per_seq: no slot
    #                              can starve another at full occupancy)
    prefill_cap_tokens: int = 512  # fairness cap: max PADDED prefill
    #                              tokens admitted per engine tick (a
    #                              single over-cap prompt still admits
    #                              when the tick has admitted nothing —
    #                              caps must not livelock)
    max_new_tokens: int = 64     # per-request default budget
    temperature: float = 0.0     # 0 = greedy; sampling knobs are engine-
    top_k: Optional[int] = None  # static (one compiled tick), seeds are
    top_p: Optional[float] = None  # per-request
    quant: str = "none"          # none | nf4 | int8 frozen-weight serving
    quant_block: Optional[int] = None  # quant block override (elements;
    # None = the format default). Under --serve_tp every sharded last dim
    # needs last/2 (nf4 packing) and last/block divisible by tp
    # (ops/quant.validate_quant_tp fails fast with the leaf path) — small
    # models need a smaller block than the 64-element default.
    eos_id: Optional[int] = None
    tp: int = 0                  # tensor-parallel degree. 0 = the
    # single-device engine (no mesh, no collectives — the pre-TP program
    # bit for bit); tp >= 1 builds a (data=1, tensor=tp) mesh over the
    # first tp local devices, shards weights per the Megatron param specs
    # and the page pools over kv heads, and shard_maps every dispatch.
    # tp=1 is pinned BIT-identical to tp=0; tp>1 divides weight+KV HBM
    # per chip and is pinned token-identical (tests/test_tp_serve.py).
    # kv_heads/n_head/d_ff must divide (parallel.tensor_parallel.
    # validate_tp — the same rule the trainer enforces).
    ep: int = 0                  # expert-parallel serving degree
    # (ISSUE 15): 0 = no expert axis. N >= 1 requires a MoE checkpoint
    # (moe_experts % N == 0) and shards the expert FFN banks over the
    # expert axis of a (data=1, expert=N, tensor=max(tp,1)) mesh — two
    # all_to_all hops per MoE block per tick, page pools untouched
    # (attention stays shard-local exactly as TP left it; under ep-only
    # the pools are replicated). Composes with tp (ep x tp devices).
    # ep=1 is pinned bit-identical to the unsharded engine; ep in {2,4}
    # (and ep x tp) pinned token-identical on CPU mesh emulation
    # (tests/test_moe_serve.py).
    ep_batch: bool = False       # batch-sharded expert-parallel decode
    # (ISSUE 16): shard the decode/prefill/verify BATCH over the expert
    # axis too — slot s lives on shard s // (max_seqs/ep), the page pools
    # shard over their block dim (P(expert, None, tensor, None)) and each
    # shard's tokens reach their experts through moe_ffn's two all_to_all
    # hops, so per-chip attention+FFN FLOPs divide by ep (a THROUGHPUT
    # lever, where plain --serve_ep only bought HBM). Host BlockTables
    # stay replicated numpy partitioned into ep page groups; allocation
    # never recompiles. Requires --serve_ep >= 1 with max_seqs and
    # num_blocks divisible by ep. ep_batch at ep=1 is pinned bit-identical
    # to the replicated-batch program; ep in {2,4} and ep x tp pinned
    # token-identical on CPU mesh emulation (tests/test_ep_batch_serve.py).
    # Prefix sharing composes group-locally (a cached page is only
    # physically present on its group's shard).
    ep_overlap: bool = False     # two-microbatch software pipelining of
    # the decode tick (ISSUE 16): the tick splits its slots into two
    # halves traced back-to-back in ONE dispatch, so microbatch B's
    # attention (page-local) has no data dependency on microbatch A's
    # expert-dispatch all_to_all and XLA's async collective scheduler can
    # overlap the two — the fabric hop hides behind compute. Outputs are
    # pinned bit-identical to the unsplit tick (attention is row-local,
    # inference MoE routing is no-drop per-token). Requires an even
    # per-shard slot count. Works with or without a mesh (off-mesh it is
    # a scheduling no-op but stays pinned, which is what the CPU tests
    # drive).
    moe_stats: bool = False      # accumulate MoE routing-load scalars
    # (valid/kept tokens vs the capacity_factor budget) into engine.stats
    # after every dispatch — the bench's capacity-utilization and
    # dropped-rate columns. Off by default: it adds per-tick host reads.
    prefix_cache: bool = False   # share prompt-prefix KV pages across
    # requests (serve/kv_cache.PrefixCache): refcounted page runs, CoW on
    # the first divergent write, LRU reclaim under pool pressure. Outputs
    # pinned identical to the unshared engine; only the physical page
    # count (and the prefill work for cache hits) changes. Composes with
    # MoE checkpoints: inference routing is no-drop per-token, so shared
    # prefix pages cannot change any expert assignment.
    speculate: str = ""          # '' = one token per decode tick;
    # '<drafter>:<k>' (ngram:4 | draft:2 ...) arms speculative decode
    # (serve/speculate.py): the drafter proposes up to k tokens per slot,
    # one batched verify dispatch scores them against this engine's model
    # on the paged cache, and the accepted prefix commits to the block
    # tables (rejected-tail pages roll back exactly). Outputs are pinned
    # identical to the non-speculative engine — greedy bit-identical,
    # sampled token-identical to the same per-request PRNG stream — the
    # knob only changes tokens per dispatch. 'draft:<k>' additionally
    # needs ServingEngine(draft_model=...).
    metrics: bool = False        # arm the request-lifecycle metrics plane
    # (serve/metrics.ServeMetrics): wall-clock TTFT / per-token sketches,
    # live gauges, drain-cadence journal events. Pinned INERT — token
    # streams are bit-identical with metrics on or off (the hooks ride
    # host work the tick already does; tests/test_serve_metrics.py).
    # Tick-domain request clocks (RequestTimes) run unconditionally —
    # they are integer bookkeeping and feed the response-record timing
    # columns even when the plane is off.
    retrace_guard: str = "warn"  # off | warn | error — the serve twin of
    # the trainer's --retrace_guard (ISSUE 19): every dispatch kind
    # (decode tick, prefill, verify, cow) hashes its operand signature
    # (rest-operand shapes/dtypes — params/pages are engine-owned stable
    # buffers) and carries a compile budget: 1 program each for
    # decode/verify/cow, one per power-of-two bucket for prefill. A
    # signature past the budget is a recompile about to happen — counted
    # as stats['serve_retraces'] + a warning, or a RuntimeError under
    # 'error' BEFORE jax pays for the lowering. Purely observational:
    # token streams are bit-identical to 'off' (the guard reads shapes,
    # never values; pinned by tests/test_serve_check.py).

    def resolved_num_blocks(self) -> int:
        return self.num_blocks or self.max_seqs * self.max_blocks_per_seq


@dataclasses.dataclass
class Request:
    req_id: Any
    tokens: List[int]                    # prompt token ids (non-empty)
    max_new_tokens: Optional[int] = None  # None = engine default
    seed: int = 0
    prefix_group: Optional[str] = None   # optional routing/accounting tag
    # for requests sharing a prompt prefix (serve/api validates it
    # strictly and echoes it on the response); the prefix cache itself
    # matches by TOKENS, so the tag never changes what is shared
    committed: List[int] = dataclasses.field(default_factory=list)
    # tokens this request already generated on ANOTHER replica (the
    # migration path, serve/replica_plane): the engine prefills
    # tokens + committed as one history and resumes the request's pinned
    # sample stream at index len(committed) — the per-request PRNG keys
    # are fold_in(key(seed), token_index), so the continued stream is
    # token-identical to never having migrated, by construction
    deadline_s: Optional[float] = None   # wall-clock budget from submit;
    # an expired request is evicted with the honest 'timeout' status
    # (partial output attached), never silently dropped


@dataclasses.dataclass
class Completion:
    req_id: Any
    prompt_len: int
    tokens: List[int]    # generated ids (EOS included when emitted)
    reason: str          # eos | length | overflow | rejected | timeout
    #                      (| failed — replica_plane's retry-budget status)
    timing: Optional[Dict[str, Any]] = None  # tick-domain request clocks
    # (serve/metrics.RequestTimes): queue_ticks always, ttft_ticks /
    # decode_ticks once a first token existed, wall ttft_ms when the
    # metrics plane is on. Echoed on the serve/api response record for
    # EVERY terminal status — a timeout with no timing would be a
    # request whose queue wait silently vanished from the books.


@dataclasses.dataclass
class RecoveryRecord:
    """The minimal per-request state a survivor needs to continue a
    request token-identically after its replica dies: prompt + committed
    tokens + seed (+ the resolved budget and deadline). The pinned
    per-request PRNG stream (``_sample_rows``: fold_in(key(seed),
    token_index)) carries the rest — re-prefilling the committed history
    and resuming at token_index = len(committed) reproduces the exact
    stream the dead replica was emitting. Exported every tick by
    :meth:`ServingEngine.export_records`; the fleet
    (serve/replica_plane.ServingFleet) shadows these OUTSIDE the replica,
    so a crash never needs to ask the dead engine anything."""

    req_id: Any
    tokens: List[int]                    # the ORIGINAL prompt
    committed: List[int]                 # tokens generated so far
    seed: int
    budget: Optional[int]                # total max_new_tokens (resolved
    #                                      for resident slots)
    prefix_group: Optional[str] = None
    deadline_at: Optional[float] = None  # absolute time.monotonic() stamp
    #                                      — survives migration unmoved

    def to_request(self) -> "Request":
        return Request(req_id=self.req_id, tokens=list(self.tokens),
                       max_new_tokens=self.budget, seed=int(self.seed),
                       prefix_group=self.prefix_group,
                       committed=list(self.committed))

    @staticmethod
    def from_request(req: "Request", committed, budget,
                     deadline_at: Optional[float]) -> "RecoveryRecord":
        """The ONE construction site (engine slot/pending exports and the
        fleet's routing-time shadow all build records here, so a future
        field cannot silently miss one of them). ``req.tokens`` is shared,
        not copied: the prompt list is immutable after submit (nothing in
        the engine or fleet writes to it) and it dominates the per-tick
        shadow-refresh cost on long prompts; ``committed`` mutates every
        tick and is always copied."""
        return RecoveryRecord(
            req_id=req.req_id, tokens=req.tokens,
            committed=list(committed), seed=int(req.seed), budget=budget,
            prefix_group=req.prefix_group, deadline_at=deadline_at)


@dataclasses.dataclass
class _Slot:
    req: Request
    budget: int          # max new tokens for this request
    cache_len: int       # tokens whose k/v are in the pages
    last_tok: int        # newest sampled token (not yet in the cache)
    gen: List[int] = dataclasses.field(default_factory=list)


def dispatch_signature(operands) -> tuple:
    """The retrace guard's operand signature: (shape, dtype) per rest
    operand — pure attribute reads (never values, never a device sync),
    so observing a dispatch costs nanoseconds on the common tick. Python
    scalars hash by type name (a scalar operand's jnp conversion always
    lands the same weak dtype for the same Python type)."""
    return tuple(
        (tuple(getattr(a, "shape", ())),
         str(getattr(a, "dtype", type(a).__name__)))
        for a in operands)


class _RetraceGuard:
    """Tick-level recompile sentinel (``ServeConfig.retrace_guard`` —
    the serving twin of train/loop's --retrace_guard, ISSUE 19). Each
    dispatch kind carries a compile BUDGET (decode/verify/cow: one
    program; prefill: one per power-of-two bucket — the engine's own
    O(log max) compile claim). The first ``budget`` distinct operand
    signatures are the legal specializations; any later NEW signature is
    a recompile the design forbids — counted into
    ``stats['serve_retraces']`` and warned once per signature, or raised
    under ``error`` BEFORE jax pays for the lowering."""

    def __init__(self, mode: str, budgets: Dict[str, int],
                 stats: Dict[str, Any]):
        self.mode = mode
        self.budgets = budgets
        self.stats = stats
        self.seen: Dict[str, set] = {}

    def observe(self, kind: str, operands) -> None:
        sig = dispatch_signature(operands)
        seen = self.seen.setdefault(kind, set())
        if sig in seen:
            return
        budget = self.budgets.get(kind, 1)
        if len(seen) < budget:
            seen.add(sig)
            return
        msg = (f"serve retrace guard: dispatch {kind!r} saw a new operand "
               f"signature past its compile budget ({budget}) — a "
               f"recompile the serving design forbids; new signature: "
               f"{sig}")
        if self.mode == "error":
            raise RuntimeError(msg)
        seen.add(sig)
        self.stats["serve_retraces"] = self.stats.get("serve_retraces", 0) + 1
        import warnings

        warnings.warn(msg, RuntimeWarning, stacklevel=3)


class ServeModel:
    """Family adapter: the paged decode hook + cache geometry the engine
    needs, built from a (params, config) pair. ``decode_paged(params,
    tokens, pages, tables, pos, valid, tp_axis, ep_axis,
    return_moe_stats)`` must return ``(logits [B,S,V] f32, pages')``
    (plus a MoE routing-stats dict when requested) —
    models/gpt2.gpt2_decode_paged and models/llama.llama_decode_paged
    are the two implementations; with ``tp_axis``/``ep_axis`` the call
    runs inside the engine's shard_map and the hook threads the axes
    into the model's Megatron-split blocks / expert banks."""

    def __init__(self, family: str, cfg: Any, params: Any,
                 decode_paged: Callable, n_layer: int, kv_heads: int,
                 head_dim: int, cache_dtype: Any,
                 max_positions: Optional[int] = None):
        self.family = family
        self.cfg = cfg
        self.params = params
        self.decode_paged = decode_paged
        self.n_layer = n_layer
        self.kv_heads = kv_heads
        self.head_dim = head_dim
        self.cache_dtype = cache_dtype
        # the model's position budget (gpt2: learned wpe rows; llama's
        # rope extrapolates but n_ctx is still the trained horizon) — the
        # engine refuses a page geometry that would silently alias/exceed
        self.max_positions = max_positions

    def param_specs(self, tensor: bool = True) -> dict:
        """The Megatron PartitionSpec tree for this family — ONE source of
        truth with the trainer (parallel/tensor_parallel and, for MoE
        checkpoints, models/gpt2.gpt2_moe_param_specs which reuses it), so
        serving and training can never shard the same checkpoint
        differently. ``tensor=False`` (an expert-only serving mesh) keeps
        attention/dense-MLP leaves replicated and shards only the expert
        banks over the expert axis."""
        if self.family == "gpt2" and getattr(self.cfg, "moe_experts", 0) > 0:
            from distributed_lion_tpu.models.gpt2 import gpt2_moe_param_specs

            return gpt2_moe_param_specs(self.cfg, tensor=tensor)
        from distributed_lion_tpu.parallel.tensor_parallel import (
            gpt2_param_specs,
            llama_param_specs,
        )

        fn = gpt2_param_specs if self.family == "gpt2" else llama_param_specs
        return fn(self.cfg)

    @staticmethod
    def for_gpt2(params: Any, cfg: Any) -> "ServeModel":
        from distributed_lion_tpu.models.gpt2 import gpt2_decode_paged

        def decode(p, toks, pages, tables, pos, valid=None, tp_axis=None,
                   ep_axis=None, return_moe_stats=False, stats_axis=None,
                   stats_lanes=None):
            return gpt2_decode_paged(p, toks, cfg, pages, tables, pos,
                                     valid, tp_axis, ep_axis,
                                     return_moe_stats, stats_axis,
                                     stats_lanes)

        return ServeModel("gpt2", cfg, params, decode, cfg.n_layer,
                          cfg.n_head, cfg.head_dim, cfg.compute_dtype,
                          max_positions=cfg.n_ctx)

    @staticmethod
    def for_llama(params: Any, cfg: Any) -> "ServeModel":
        from distributed_lion_tpu.models.llama import llama_decode_paged

        def decode(p, toks, pages, tables, pos, valid=None, tp_axis=None,
                   ep_axis=None, return_moe_stats=False, stats_axis=None,
                   stats_lanes=None):
            # llama has no MoE blocks; the engine refuses --serve_ep for
            # it at build, so these can never be set here
            assert ep_axis is None and not return_moe_stats
            assert stats_axis is None and stats_lanes is None
            return llama_decode_paged(p, toks, cfg, pages, tables, pos,
                                      valid, tp_axis)

        return ServeModel("llama", cfg, params, decode, cfg.n_layer,
                          cfg.n_kv_head, cfg.head_dim, cfg.compute_dtype,
                          max_positions=cfg.n_ctx)


def weight_bytes(params: Any) -> int:
    """Actual storage bytes of a (possibly quantized) weight tree —
    QuantizedTensor leaves count packed codes + absmax scales, dense
    leaves their array bytes. The bench's NF4-vs-bf16 column."""
    import jax

    from distributed_lion_tpu.ops.quant import QuantizedTensor

    total = 0
    for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedTensor)):
        if isinstance(leaf, QuantizedTensor):
            total += leaf.codes.size * leaf.codes.dtype.itemsize
            total += leaf.absmax.size * leaf.absmax.dtype.itemsize
        else:
            total += leaf.size * leaf.dtype.itemsize
    return total


def _sample_rows(logits, seeds, counts, temperature: float,
                 top_k: Optional[int], top_p: Optional[float]):
    """[B, V] logits → [B] tokens with PER-ROW keys derived from
    (request seed, generated-token index) — slot- and batch-independent
    draws (see module doc). Greedy when ``temperature == 0``."""
    import jax
    import jax.numpy as jnp

    from distributed_lion_tpu.models.generate import filter_logits

    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    filtered = filter_logits(logits, temperature, top_k, top_p)
    keys = jax.vmap(
        lambda s, c: jax.random.fold_in(jax.random.key(s), c))(seeds, counts)
    return jax.vmap(jax.random.categorical)(keys, filtered)


def _flat_leaves(tree, is_leaf=None):
    import jax

    return jax.tree.flatten(tree, is_leaf=is_leaf)


def _shard_params(params: Any, specs: Any, mesh) -> Any:
    """Place a (possibly NF4/int8-quantized) weight tree onto the TP mesh
    per its Megatron PartitionSpec tree. Quantized leaves shard with the
    SAME spec as their dense twin: the shaped layout keeps every leading
    dim 1:1 with the dense weight and blocks run along the last dim only
    (ops/quant), so codes and absmax both slice cleanly."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from distributed_lion_tpu.ops.quant import QuantizedTensor

    leaves, treedef = _flat_leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor))
    spec_leaves, _ = _flat_leaves(
        specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
    assert len(leaves) == len(spec_leaves), \
        "param tree and spec tree disagree"

    def place(w, spec):
        s = NamedSharding(mesh, spec)
        if isinstance(w, QuantizedTensor):
            return QuantizedTensor(jax.device_put(w.codes, s),
                                   jax.device_put(w.absmax, s),
                                   w.shape, w.fmt, w.block, w.layout)
        return jax.device_put(w, s)

    return jax.tree.unflatten(
        treedef, [place(w, s) for w, s in zip(leaves, spec_leaves)])


class ServingEngine:
    """See module doc. Host-side driver: ``submit`` requests, call
    ``step()`` per tick (or ``run()`` to drain a workload), collect
    :class:`Completion`s."""

    def __init__(self, model: ServeModel, cfg: ServeConfig,
                 draft_model: Optional[ServeModel] = None,
                 time_fn: Callable[[], float] = time.monotonic):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.model = model
        self.cfg = cfg
        # the injectable clock (graft-check DLT011): every wall-clock
        # read in the engine goes through ``self._now`` so deadline /
        # latency behavior is testable without real sleeps; the metrics
        # plane (when armed) shares the same clock
        self._now = time_fn
        params = model.params
        if cfg.quant not in ("none", "nf4", "int8"):
            raise ValueError(f"unknown quant mode {cfg.quant!r}")
        if cfg.retrace_guard not in ("off", "warn", "error"):
            raise ValueError(
                f"unknown retrace_guard mode {cfg.retrace_guard!r} "
                "(off | warn | error)")
        if cfg.quant != "none":
            from distributed_lion_tpu.ops.quant import quantize_tree

            params = quantize_tree(params, cfg.quant,
                                   block=cfg.quant_block)
        horizon = cfg.block_size * cfg.max_blocks_per_seq
        if model.max_positions is not None and horizon > model.max_positions:
            raise ValueError(
                f"page geometry allows {horizon} tokens/seq but the model's "
                f"position budget is {model.max_positions} (n_ctx); shrink "
                "--block_size/--max_blocks_per_seq — positions past the "
                "trained horizon would silently alias")

        # ---- tensor/expert-parallel mesh (tp=0, ep=0: the single-device
        # program, bit for bit)
        self._mesh = None
        self._param_specs = None
        self._pages_spec = None
        self._tp_axis = TENSOR_AXIS if cfg.tp else None
        self._ep_axis = EXPERT_AXIS if cfg.ep else None
        self._ep_batch = bool(cfg.ep_batch)
        self._ep_overlap = bool(cfg.ep_overlap)
        self._moe_stats = bool(cfg.moe_stats
                               and getattr(model.cfg, "moe_experts", 0) > 0)
        if cfg.ep_batch:
            if not cfg.ep:
                raise ValueError(
                    "--serve_ep_batch shards the decode batch over the "
                    "expert axis — it needs --serve_ep >= 1")
            if cfg.max_seqs % cfg.ep:
                raise ValueError(
                    f"--serve_ep_batch needs max_seqs ({cfg.max_seqs}) "
                    f"divisible by --serve_ep {cfg.ep}: slots partition "
                    "evenly over the expert shards")
            if cfg.resolved_num_blocks() % cfg.ep:
                raise ValueError(
                    f"--serve_ep_batch needs num_blocks "
                    f"({cfg.resolved_num_blocks()}) divisible by "
                    f"--serve_ep {cfg.ep}: the page pool shards over its "
                    "block dim")
        groups = cfg.ep if cfg.ep_batch else 1
        if cfg.ep_overlap:
            local_slots = cfg.max_seqs // groups
            if local_slots % 2 or local_slots < 2:
                raise ValueError(
                    f"--serve_ep_overlap splits each shard's "
                    f"{local_slots} decode slots into two microbatches — "
                    "the per-shard slot count must be even (and >= 2)")
        pages_sharding = None
        if cfg.ep:
            n_experts = getattr(model.cfg, "moe_experts", 0)
            if n_experts <= 0:
                raise ValueError(
                    f"--serve_ep {cfg.ep} needs a MoE checkpoint "
                    "(moe_experts > 0): the expert axis shards expert FFN "
                    "banks — dense checkpoints shard with --serve_tp")
            if n_experts % cfg.ep:
                raise ValueError(
                    f"moe_experts ({n_experts}) not divisible by "
                    f"--serve_ep {cfg.ep}: the expert banks shard over "
                    "the expert axis")
        if cfg.tp or cfg.ep:
            from distributed_lion_tpu.parallel.mesh import make_mesh

            if cfg.tp:
                from distributed_lion_tpu.parallel.tensor_parallel import (
                    validate_tp,
                )

                validate_tp(model.cfg, cfg.tp, model.family)
                if model.kv_heads % cfg.tp:
                    raise ValueError(
                        f"kv heads ({model.kv_heads}) not divisible by "
                        f"--serve_tp {cfg.tp}: the page pool shards over "
                        "the kv-head axis")
            devices = jax.devices()
            need = max(cfg.tp, 1) * max(cfg.ep, 1)
            if len(devices) < need:
                raise ValueError(
                    f"--serve_tp {cfg.tp} x --serve_ep {cfg.ep} needs "
                    f"{need} devices, backend has {len(devices)}")
            self._mesh = make_mesh(data=1, tensor=max(cfg.tp, 1),
                                   expert=max(cfg.ep, 1),
                                   devices=devices[:need])
            specs = model.param_specs(tensor=bool(cfg.tp))
            if cfg.quant != "none":
                from distributed_lion_tpu.ops.quant import validate_quant_tp

                if cfg.tp:
                    validate_quant_tp(params, specs, cfg.tp, TENSOR_AXIS)
                if cfg.ep > 1:
                    # expert banks shard their LEADING dim — the shaped
                    # quant layout keeps leading dims 1:1 with the dense
                    # weight, so the same validator covers the expert axis
                    validate_quant_tp(params, specs, cfg.ep, EXPERT_AXIS)
            params = _shard_params(params, specs, self._mesh)
            self._param_specs = specs
            # batch-sharded ep additionally shards the pool over its
            # BLOCK dim (each shard holds its slot group's pages); the
            # kv-head axis stays tensor-sharded either way
            pool_spec = (P(EXPERT_AXIS, None, TENSOR_AXIS, None)
                         if cfg.ep_batch
                         else P(None, None, TENSOR_AXIS, None))
            self._pages_spec = [{"k": pool_spec, "v": pool_spec}
                                for _ in range(model.n_layer)]
            pages_sharding = NamedSharding(self._mesh, pool_spec)
        self.params = params

        self.tables = BlockTables(cfg.resolved_num_blocks(), cfg.block_size,
                                  cfg.max_seqs, cfg.max_blocks_per_seq,
                                  groups=groups)
        self.pages = init_pages(model.n_layer, cfg.resolved_num_blocks(),
                                cfg.block_size, model.kv_heads,
                                model.head_dim, model.cache_dtype)
        if pages_sharding is not None:
            self.pages = [
                {k: jax.device_put(v, pages_sharding)
                 for k, v in layer.items()} for layer in self.pages]
        # one PrefixCache per pool group (sharing is group-local under
        # batch-sharded ep: a cached page is physically present only on
        # its group's shard); ``self.prefix`` stays the groups==1 alias
        # the existing tests/bench read
        if cfg.prefix_cache:
            if self.tables.groups == 1:
                self._prefix_caches = [PrefixCache(self.tables)]
            else:
                self._prefix_caches = [PrefixCache(self.tables, g)
                                       for g in range(self.tables.groups)]
            self.prefix = self._prefix_caches[0]
        else:
            self._prefix_caches = None
            self.prefix = None
        self.slots: List[Optional[_Slot]] = [None] * cfg.max_seqs
        self.pending: deque = deque()
        # req_id -> absolute time.monotonic() deadline (requests with a
        # deadline_s, or an inherited stamp from a pre-migration submit)
        self._deadline_at: Dict[Any, float] = {}
        self.stats = {"ticks": 0, "decode_ticks": 0, "prefill_dispatches": 0,
                      "decode_tokens": 0, "prefill_tokens": 0,
                      "padded_prefill_tokens": 0, "evictions": 0,
                      "freed_pages": 0, "timeouts": 0, "resumed_requests": 0,
                      "resumed_tokens": 0}
        if self.prefix is not None:
            self.stats.update(prefix_hits=0, shared_tokens=0, cow_copies=0,
                              reclaimed_pages=0)
        if self._moe_stats:
            # routing load vs the capacity_factor budget (moe_ffn stats;
            # serving itself never drops — inference routing is no-drop)
            self.stats.update(moe_valid_tokens=0.0, moe_kept_tokens=0.0,
                              moe_capacity_slots=0.0)
        # tick-domain request clocks: always on (integer bookkeeping on
        # events the loop already handles); the wall-clock/sketch plane
        # only when armed. ``self.metrics`` may be replaced before the
        # first submit with a ServeMetrics carrying an SLOMonitor
        # (cli/run_serve wires --slo_* that way).
        self.times = RequestTimes()
        self.metrics: Optional[ServeMetrics] = (
            ServeMetrics(self.times, time_fn=time_fn)
            if cfg.metrics else None)
        # dispatch registry (ISSUE 19): name -> the jitted callable plus
        # the pre-jit body and jit options, so analysis/serve_check can
        # walk the ACTUAL compiled programs (jaxprs + lowered MLIR) and
        # compile_counts() can enumerate the live jit caches
        self._dispatches: Dict[str, Dict[str, Any]] = {}
        self._retrace_guard: Optional[_RetraceGuard] = None
        if cfg.retrace_guard != "off":
            self.stats["serve_retraces"] = 0
            self._retrace_guard = _RetraceGuard(
                cfg.retrace_guard, self.compile_budget(), self.stats)

        samp = (cfg.temperature, cfg.top_k, cfg.top_p)
        tp_axis, ep_axis = self._tp_axis, self._ep_axis
        moe_stats = self._moe_stats
        # batch-sharded ep: each shard routes only its batch slice, so
        # the routing-load counters must psum over the expert axis to
        # stay global (parallel/expert.moe_ffn stats_axis)
        stats_axis = ep_axis if cfg.ep_batch else None
        overlap = self._ep_overlap

        def decode_tick(params, pages, tables, lens, last, act, seeds,
                        counts):
            # act [S] bool: the engine's valid-lane mask for the tick —
            # inactive (sentinel) slots are dead lanes for expert routing
            # and for the scatter (which their sentinel rows drop anyway).
            # Under ep_batch every operand here is this shard's LOCAL
            # slot slice and ``tables`` carries group-local page ids.
            def run(pages, sl):
                out = model.decode_paged(
                    params, last[sl][:, None], pages, tables[sl], lens[sl],
                    act[sl][:, None], tp_axis=tp_axis, ep_axis=ep_axis,
                    return_moe_stats=moe_stats, stats_axis=stats_axis)
                return out[0], (out[2] if moe_stats else {}), out[1]

            if not overlap:
                logits, st, pages = run(pages, slice(None))
            else:
                # two microbatches traced back-to-back in ONE program:
                # B's attention depends on A only through the page
                # buffers (disjoint rows), NOT on A's expert all_to_all —
                # XLA's async collective scheduling overlaps the two.
                # Bit-identical to the unsplit tick: attention is
                # row-local and inference MoE routing is no-drop
                # per-token (capacity_override = the microbatch size
                # still never drops).
                n = lens.shape[0]
                la, sa, pages = run(pages, slice(0, n // 2))
                lb, sb, pages = run(pages, slice(n // 2, None))
                logits = jnp.concatenate([la, lb], axis=0)
                st = {k: sa[k] + sb[k] for k in sa} if moe_stats else {}
            return (_sample_rows(logits[:, -1], seeds, counts, *samp),
                    st), pages

        def prefill(params, pages, tables, toks, start, length, seed, count):
            # toks [1, P] — the prompt SUFFIX not covered by shared prefix
            # pages, scattered at absolute positions start..start+P-1
            # (start == 0 without prefix sharing: the whole prompt).
            # Under ep_batch the batch-1 prefill stays one dispatch: every
            # shard traces the same program, but only the OWNER group's
            # shard receives the slot's table row and the true length —
            # the others see an all-sentinel row and length 0 (all lanes
            # invalid), so their scatters drop, their lanes consume zero
            # expert capacity, and their sampled lane is garbage the host
            # never reads (the token output is expert-sharded [ep]; the
            # host picks the owner's entry).
            L = jnp.reshape(length, (-1,))[0]
            valid = jnp.arange(toks.shape[1])[None, :] < L
            # stats_lanes: non-owner groups replay the width with every
            # lane invalid — fake lanes that must not inflate the stats
            # capacity budget past the unsharded prefill's (ceil is
            # nonlinear, so the budget can't be corrected after the fact)
            out = model.decode_paged(params, toks, pages, tables,
                                     start, valid, tp_axis=tp_axis,
                                     ep_axis=ep_axis,
                                     return_moe_stats=moe_stats,
                                     stats_axis=stats_axis,
                                     stats_lanes=(toks.shape[1]
                                                  if stats_axis else None))
            logits, pages = out[0], out[1]
            st = out[2] if moe_stats else {}
            last = jax.lax.dynamic_index_in_dim(
                logits[0], jnp.maximum(L - 1, 0), 0, keepdims=False)
            tok = _sample_rows(last[None], seed[None], count[None], *samp)
            return (tok, st), pages

        def cow_copy(pages, src, dst):
            from distributed_lion_tpu.ops.attention import paged_copy_pages

            # src/dst arrive [width] (replicated) or [1, width] (this
            # shard's row of the grouped layout) — flatten either way
            return paged_copy_pages(pages, src.reshape(-1), dst.reshape(-1))

        if cfg.ep_batch:
            from jax.sharding import PartitionSpec as P

            bsp, rep = P(EXPERT_AXIS), P()
            tab = P(EXPERT_AXIS, None)
            self._decode_tick = self._jit_paged(
                decode_tick, n_rest=6,
                rest_specs=(tab, bsp, bsp, bsp, bsp, bsp),
                out_spec=(bsp, rep), name="decode")
            self._prefill = self._jit_paged(
                prefill, n_rest=6,
                rest_specs=(tab, rep, bsp, bsp, rep, rep),
                out_spec=(bsp, rep), name="prefill")
        else:
            self._decode_tick = self._jit_paged(decode_tick, n_rest=6,
                                                name="decode")
            self._prefill = self._jit_paged(prefill, n_rest=6,
                                            name="prefill")
        self._cow = self._jit_cow(cow_copy)

        self._speculator = None
        if cfg.speculate:
            from distributed_lion_tpu.serve.speculate import build_speculator

            self._speculator = build_speculator(self, cfg.speculate,
                                                draft_model)

    # ------------------------------------------------------- TP dispatch
    def _register_dispatch(self, name: Optional[str], jitted, inner,
                           donate, rest_specs, out_spec) -> None:
        """Record a jitted serve dispatch for the observability hooks:
        ``compile_counts()`` reads the live jit caches,
        analysis/serve_check walks the jaxprs/MLIR of the same callables
        the ticks run (``inner`` is the pre-jit body — the shard_map'd
        program under a mesh — so the check can re-jit it with donation
        forced on backends where the engine turns donation off)."""
        if name is None:
            return
        self._dispatches[name] = {
            "jitted": jitted, "inner": inner, "donate": tuple(donate),
            "rest_specs": rest_specs, "out_spec": out_spec,
        }

    def compile_counts(self) -> Dict[str, int]:
        """Distinct compiled programs per registered dispatch, from jax's
        own jit caches — the measurable side of "O(log max) prefill
        compiles, ONE decode program". The compile-budget contract
        (analysis/serve_check and the retrace guard) pins these against
        :meth:`compile_budget` after a mixed workload."""
        out: Dict[str, int] = {}
        for name, d in self._dispatches.items():
            size = getattr(d["jitted"], "_cache_size", None)
            out[name] = int(size()) if callable(size) else -1
        return out

    def compile_budget(self) -> Dict[str, int]:
        """Max legal distinct lowerings per dispatch kind: decode /
        verify / cow are ONE fixed-shape program each; prefill gets one
        per power-of-two page bucket (serve/kv_cache.bucket_tokens — the
        O(log max) claim made countable). The draft-model mirror's own
        prefill buckets identically."""
        cap = self.cfg.block_size * self.cfg.max_blocks_per_seq
        buckets = {bucket_tokens(n, self.cfg.block_size,
                                 self.cfg.max_blocks_per_seq)
                   for n in range(1, cap + 1)}
        budget = {"decode": 1, "cow": 1, "prefill": len(buckets)}
        if self.cfg.speculate:
            budget["verify"] = 1
            budget["draft_prefill"] = len(buckets)
            budget["draft_step"] = 1
        return budget

    def _guard(self, kind: str, operands) -> None:
        """Retrace-guard hook, called immediately before each dispatch
        with its rest operands (params/pages are engine-owned stable
        buffers and never change signature)."""
        if self._retrace_guard is not None:
            self._retrace_guard.observe(kind, operands)

    def _jit_paged(self, fn, n_rest: int, rest_specs=None, out_spec=None,
                   name: Optional[str] = None):
        """jit a dispatch ``fn(params, pages, *rest) -> (out, pages)``;
        under TP the body is shard_map'd over the serving mesh — params
        and pages sharded per their spec trees, every host-built operand
        (tables, lens, tokens, seeds) replicated, the sampled tokens
        replicated back out (each rank computes identical logits: see the
        model hooks). ``check_vma=False`` mirrors the trainer's usage.

        Batch-sharded ep (ISSUE 16) passes ``rest_specs`` (one
        PartitionSpec per rest operand — slot-leading arrays shard
        ``P(EXPERT_AXIS)``) and ``out_spec`` (the spec-prefix for the
        first output, e.g. ``(P(EXPERT_AXIS), P())`` for
        expert-sharded sampled tokens + replicated psummed stats);
        speculative verify reuses the same hooks (serve/speculate.py)."""
        import jax

        donate = (1,) if jax.default_backend() != "cpu" else ()
        if self._mesh is None:
            jitted = jax.jit(fn, donate_argnums=donate)
            self._register_dispatch(name, jitted, fn, donate, None, None)
            return jitted
        from jax.sharding import PartitionSpec as P

        rep = P()
        if rest_specs is None:
            rest_specs = (rep,) * n_rest
        if out_spec is None:
            out_spec = rep
        body = jax.shard_map(
            fn, mesh=self._mesh,
            in_specs=(self._param_specs, self._pages_spec)
            + tuple(rest_specs),
            out_specs=(out_spec, self._pages_spec), check_vma=False)
        jitted = jax.jit(body, donate_argnums=donate)
        self._register_dispatch(name, jitted, body, donate,
                                tuple(rest_specs), out_spec)
        return jitted

    def _jit_cow(self, fn):
        """jit the CoW page-copy ``fn(pages, src, dst) -> pages`` (pages
        donated; shard-local under TP — page ids are replicated host
        math, the kv-head axis stays put). Under batch-sharded ep the
        src/dst ids arrive as the grouped ``[ep, width]`` layout, each
        shard copying only its own group's rows with LOCAL ids."""
        import jax

        donate = (0,) if jax.default_backend() != "cpu" else ()
        if self._mesh is None:
            jitted = jax.jit(fn, donate_argnums=donate)
            self._register_dispatch("cow", jitted, fn, donate, None, None)
            return jitted
        from jax.sharding import PartitionSpec as P

        rep = P()
        idx = P(EXPERT_AXIS) if self._ep_batch else rep
        body = jax.shard_map(
            fn, mesh=self._mesh,
            in_specs=(self._pages_spec, idx, idx),
            out_specs=self._pages_spec, check_vma=False)
        jitted = jax.jit(body, donate_argnums=donate)
        self._register_dispatch("cow", jitted, body, donate,
                                (idx, idx), None)
        return jitted

    def _absorb_moe_stats(self, st) -> None:
        """Fold a dispatch's MoE routing-load scalars into engine.stats —
        a no-op ({}) unless ``ServeConfig.moe_stats`` is armed on a MoE
        checkpoint, so the common tick pays zero extra host reads."""
        if not st:
            return
        self.stats["moe_valid_tokens"] += float(np.asarray(st["valid"]))
        self.stats["moe_kept_tokens"] += float(np.asarray(st["kept"]))
        self.stats["moe_capacity_slots"] += float(
            np.asarray(st["capacity_slots"]))

    # ------------------------------------------------------------- intake
    def submit(self, req: Request, deadline_at: Optional[float] = None
               ) -> None:
        """Queue a request. ``deadline_at`` (absolute ``time.monotonic()``)
        overrides the fresh ``deadline_s`` stamp — the migration path: a
        request's wall-clock budget started at its ORIGINAL submission and
        must not reset when a survivor re-admits it."""
        if deadline_at is None and req.deadline_s is not None:
            deadline_at = self._now() + float(req.deadline_s)
        if deadline_at is not None:
            self._deadline_at[req.req_id] = float(deadline_at)
        self.times.submitted(req.req_id, self.stats["ticks"])
        if self.metrics is not None:
            self.metrics.on_submit(req.req_id)
        self.pending.append(req)

    def _finish_timing(self, req_id, status: str) -> Dict[str, Any]:
        """Retire the request's clocks into a timing dict (fed through
        the metrics plane when armed, which adds wall ``ttft_ms``) and
        journal the terminal ``serve_finish`` event — the per-request
        record run_analyze --serve builds waterfalls from."""
        timing = self.times.finished(req_id, self.stats["ticks"])
        if self.metrics is not None:
            timing = self.metrics.on_finish(req_id, timing, status,
                                            tick=self.stats["ticks"])
        journal.active().event("serve_finish", req_id=str(req_id),
                               reason=status, **timing)
        return timing

    def has_work(self) -> bool:
        return bool(self.pending) or any(s is not None for s in self.slots)

    def export_records(self) -> List[RecoveryRecord]:
        """Snapshot every unfinished request (resident slots + the pending
        queue) as :class:`RecoveryRecord`s — pure host-side table/list
        reads, no device sync. The fleet copies these OUT of the replica
        each tick so a crash recovers from the shadow, never from the
        dead engine."""
        recs = []
        for s in self.slots:
            if s is None:
                continue
            recs.append(RecoveryRecord.from_request(
                s.req, s.gen, int(s.budget),
                self._deadline_at.get(s.req.req_id)))
        for req in self.pending:
            recs.append(RecoveryRecord.from_request(
                req, req.committed, req.max_new_tokens,
                self._deadline_at.get(req.req_id)))
        return recs

    def export_prefix_chains(self) -> List[List[int]]:
        """The prefix cache's maximal cached token chains (all pool
        groups merged, deduped) — what fleet-restart persistence banks so
        a NEW engine can warm-start its page pool by re-prefilling each
        shared chain once instead of cold prefilling it per request.
        Empty without ``prefix_cache`` (nothing shared, nothing to save).
        Host-side dict walks only — no device sync."""
        if not self._prefix_caches:
            return []
        seen = set()
        for cache in self._prefix_caches:
            for chain in cache.chains():
                seen.add(tuple(chain))
        return [list(k) for k in sorted(seen, key=lambda k: (len(k), k))]

    def _bucket(self, n: int) -> int:
        return bucket_tokens(n, self.cfg.block_size,
                             self.cfg.max_blocks_per_seq)

    def _prefix_for(self, slot: int) -> PrefixCache:
        """The prefix cache serving ``slot``'s pool group (the one cache
        when the batch is not expert-sharded)."""
        return self._prefix_caches[self.tables.group_of(slot)]

    def _device_tables(self):
        """The decode tick's device view of the block tables: the global
        numpy table as-is, or — under batch-sharded ep — group-LOCAL page
        ids (sentinel == the local pool size, inert on every shard's
        scatter/gather exactly like the global sentinel is globally)."""
        import jax.numpy as jnp

        bt = self.tables
        if not self._ep_batch:
            return jnp.asarray(bt.tables)
        base = (np.arange(bt.max_seqs, dtype=np.int32)
                // bt.slots_per_group) * bt.blocks_per_group
        local = np.where(bt.tables == bt.sentinel, bt.blocks_per_group,
                         bt.tables - base[:, None]).astype(np.int32)
        return jnp.asarray(local)

    # ------------------------------------------------- page bookkeeping
    def _grow(self, slot: int, n_tokens: int) -> bool:
        """``tables.grow`` with prefix-cache reclaim as the fallback: a
        pool exhausted by CACHED pages (refs held only by the cache) is
        not full — LRU chains are dropped until the grow fits or the
        cache is empty. Overflow semantics beyond that are the caller's
        (unchanged from the unshared engine)."""
        if self.tables.grow(slot, n_tokens):
            return True
        if self.prefix is None:
            return False
        if self.tables.blocks_for(n_tokens) > self.tables.max_blocks_per_seq:
            return False  # width cap, not pool pressure: no reclaim helps
        need = (self.tables.blocks_for(n_tokens)
                - int(self.tables.owned[slot]))
        self.stats["reclaimed_pages"] += self._prefix_for(slot).reclaim(need)
        return self.tables.grow(slot, n_tokens)

    def _cow_if_shared(self, slot: int, pos: int, pairs: List[tuple]) -> bool:
        """Queue a copy-on-write for the page holding ``pos`` when it is
        shared (refs > 1) — the caller flushes ``pairs`` as ONE device
        dispatch before any write lands. Returns False only when no page
        can be found even after cache reclaim (caller overflow-evicts)."""
        if self.prefix is None or not self.tables.shared_at(slot, pos):
            return True
        pair = self.tables.cow(slot, pos)
        if pair is None:
            self.stats["reclaimed_pages"] += self._prefix_for(slot).reclaim(1)
            if not self.tables.shared_at(slot, pos):
                # the reclaim dropped the cache's own ref on this page —
                # it is private now, no copy needed (retrying cow here
                # would trip its shared-page precondition)
                return True
            pair = self.tables.cow(slot, pos)
            if pair is None:
                return False
        pairs.append(pair)
        self.stats["cow_copies"] += 1
        return True

    def _flush_cow(self, pairs: List[tuple]) -> None:
        """Dispatch the tick's queued page copies (one fixed-width jitted
        program, sentinel-padded — no recompiles as the copy count
        varies). A no-op on an empty queue: the common tick pays zero."""
        if not pairs:
            return
        import jax.numpy as jnp

        bt = self.tables
        if self._ep_batch:
            # grouped layout [ep, width]: each shard receives its group's
            # row with LOCAL page ids (a CoW pair is always intra-group —
            # cow() mints from the slot's own group), padded with the
            # LOCAL sentinel so unused lanes drop on device
            width = bt.slots_per_group
            lsent = bt.blocks_per_group
            src = np.full((bt.groups, width), lsent, np.int32)
            dst = np.full((bt.groups, width), lsent, np.int32)
            fill = np.zeros((bt.groups,), np.int32)
            for s, d in pairs:
                g = s // bt.blocks_per_group
                base = g * bt.blocks_per_group
                i = int(fill[g])
                fill[g] += 1
                src[g, i] = s - base
                dst[g, i] = d - base
            assert fill.max() <= width, "more CoW copies than group slots"
        else:
            width = self.cfg.max_seqs
            assert len(pairs) <= width, "more CoW copies than slots"
            sentinel = bt.sentinel
            src = np.full((width,), sentinel, np.int32)
            dst = np.full((width,), sentinel, np.int32)
            for i, (s, d) in enumerate(pairs):
                src[i], dst[i] = s, d
        with journal.active().span("serve/cow", copies=len(pairs)):
            src_dev, dst_dev = jnp.asarray(src), jnp.asarray(dst)
            self._guard("cow", (src_dev, dst_dev))
            self.pages = self._cow(self.pages, src_dev, dst_dev)

    # -------------------------------------------------------------- ticks
    def _dispatch_prefill(self, req: Request, slot: int, covered: int,
                          suffix: List[int], padded: int) -> int:
        """Ship ONE admitted request's prefill and return its sampled
        first token. All device-array construction for the dispatch
        happens here, at the dispatch boundary — the admission loop's
        body stays numpy/table math (graft-check DLT010 pins that
        shape), and the readback is ONE host sync per prefill."""
        import jax.numpy as jnp

        toks = np.zeros((1, padded), np.int32)
        toks[0, :len(suffix)] = suffix
        bt = self.tables
        g = bt.group_of(slot)
        if self._ep_batch:
            # only the OWNER group's shard gets the real table row
            # (LOCAL ids) and the true length — the other shards see
            # all-sentinel + length 0 (every lane invalid): their
            # scatters drop, their lanes consume zero expert capacity,
            # their sampled lane is never read (the token output is
            # expert-sharded [ep])
            tab = np.full((bt.groups, bt.max_blocks_per_seq),
                          bt.blocks_per_group, np.int32)
            row = bt.tables[slot]
            tab[g] = np.where(row == bt.sentinel,
                              bt.blocks_per_group,
                              row - bt.group_base(g))
            start_h = np.zeros((bt.groups,), np.int32)
            start_h[g] = covered
            len_h = np.zeros((bt.groups,), np.int32)
            len_h[g] = len(suffix)
            tab_dev = jnp.asarray(tab)
            start_dev = jnp.asarray(start_h)
            len_dev = jnp.asarray(len_h)
        else:
            tab_dev = jnp.asarray(bt.tables[slot:slot + 1])
            start_dev = jnp.full((1,), covered, jnp.int32)
            len_dev = jnp.int32(len(suffix))
        # the sample index resumes at len(committed): the key for this
        # draw is fold_in(key(seed), len(committed)) — the exact key the
        # pre-migration engine would use next
        rest = (tab_dev, jnp.asarray(toks), start_dev, len_dev,
                jnp.uint32(req.seed), jnp.int32(len(req.committed)))
        self._guard("prefill", rest)
        (tok, st), self.pages = self._prefill(self.params, self.pages,
                                              *rest)
        # ONE host sync per prefill dispatch (the owner group's lane
        # under ep_batch; the only lane otherwise)
        first = int(np.asarray(tok).reshape(-1)[g if self._ep_batch else 0])
        self._absorb_moe_stats(st)
        return first

    def _admit(self, completions: List[Completion]) -> None:
        budget = self.cfg.prefill_cap_tokens
        admitted = 0
        jrnl = journal.active()
        while self.pending:
            req = self.pending[0]
            # a migrated request prefills its WHOLE history — prompt plus
            # the tokens it already generated elsewhere — and resumes the
            # pinned sample stream at index len(committed) (see Request)
            hist = list(req.tokens) + list(req.committed)
            L = len(hist)
            cap = self.tables.max_tokens_per_seq
            if not req.tokens or len(req.tokens) > cap - 1:
                # -1: a prompt must leave room for one decode write
                self.pending.popleft()
                self._deadline_at.pop(req.req_id, None)
                completions.append(Completion(
                    req.req_id, len(req.tokens), list(req.committed),
                    "rejected", timing=self._finish_timing(
                        req.req_id, "rejected")))
                continue
            if L > cap:
                # a resumption already past the horizon: the uninterrupted
                # run overflow-evicted at exactly this point, delivering
                # these committed tokens — same status, same tokens, no
                # pointless prefill (L == cap still admits: the history
                # fills the table, one token samples, and the NEXT tick's
                # failed grow overflow-evicts like the uninterrupted run)
                self.pending.popleft()
                self._deadline_at.pop(req.req_id, None)
                completions.append(Completion(
                    req.req_id, len(req.tokens), list(req.committed),
                    "overflow", timing=self._finish_timing(
                        req.req_id, "overflow")))
                continue
            slot = self.tables.find_free_slot()
            if slot is None:
                break  # no slot: wait for evictions — checked BEFORE the
                # prefix match so a stalled queue costs O(1) per tick,
                # not a full match walk (which would also touch LRU
                # recency for a request that cannot admit)
            run, covered = ([], 0)
            if self.prefix is not None:
                run, covered = self._prefix_for(slot).match(hist)
            P = self._bucket(L - covered)
            if admitted and P > budget:
                break  # fairness cap — but never starve an empty tick
            if run:
                self.tables.share(slot, run)
            cow_pairs: List[tuple] = []
            if not (self._grow(slot, min(L + 1, cap))
                    and self._cow_if_shared(slot, covered, cow_pairs)):
                # no pages even after reclaim: roll the slot back EMPTY
                # (all-or-nothing — a half-reserved slot strands refs)
                self.stats["freed_pages"] += self.tables.free_slot(slot)
                break
            self.pending.popleft()
            self._flush_cow(cow_pairs)
            suffix = hist[covered:]
            with jrnl.span("serve/prefill", req_id=str(req.req_id),
                           prompt_len=L, padded=P, slot=slot,
                           shared=covered, resumed=len(req.committed)):
                first = self._dispatch_prefill(req, slot, covered,
                                               suffix, P)
            budget -= P
            admitted += 1
            self.stats["prefill_dispatches"] += 1
            self.stats["prefill_tokens"] += len(suffix)
            self.stats["padded_prefill_tokens"] += P
            if req.committed:
                self.stats["resumed_requests"] += 1
                self.stats["resumed_tokens"] += len(req.committed)
            if self.prefix is not None:
                if covered:
                    self.stats["prefix_hits"] += 1
                    self.stats["shared_tokens"] += covered
                self._prefix_for(slot).register(slot, hist)
            slot_state = _Slot(req=req, cache_len=L, last_tok=first,
                               budget=(req.max_new_tokens
                                       or self.cfg.max_new_tokens))
            slot_state.gen = list(req.committed) + [first]
            self.slots[slot] = slot_state
            self.times.first_token(req.req_id, self.stats["ticks"])
            if self.metrics is not None:
                self.metrics.on_first_token(req.req_id)
            if self._speculator is not None:
                self._speculator.on_admit(slot, hist, len(req.committed))
            self._maybe_finish(slot, completions)

    def _maybe_finish(self, slot: int, completions: List[Completion],
                      overflow: bool = False, timeout: bool = False) -> None:
        s = self.slots[slot]
        reason = None
        if overflow:
            reason = "overflow"
        elif timeout:
            reason = "timeout"
        elif self.cfg.eos_id is not None and s.gen and \
                s.gen[-1] == self.cfg.eos_id:
            reason = "eos"
        elif len(s.gen) >= s.budget:
            reason = "length"
        if reason is None:
            return
        with journal.active().span("serve/evict", req_id=str(s.req.req_id),
                                   slot=slot, reason=reason,
                                   n_generated=len(s.gen)):
            # refcount-honest accounting: evicting a sharer whose pages
            # all outlive it (prefix cache / other slots) frees ZERO
            # physical pages — freed_pages records what really returned
            freed = self.tables.free_slot(slot)
            self.stats["freed_pages"] += freed
            self.slots[slot] = None
            self.stats["evictions"] += 1
            if reason == "timeout":
                self.stats["timeouts"] += 1
            if self._speculator is not None:
                self._speculator.on_evict(slot)
        self._deadline_at.pop(s.req.req_id, None)
        completions.append(Completion(
            s.req.req_id, len(s.req.tokens), list(s.gen), reason,
            timing=self._finish_timing(s.req.req_id, reason)))

    def _decode(self, completions: List[Completion]) -> None:
        import jax.numpy as jnp

        active = [i for i, s in enumerate(self.slots) if s is not None]
        if not active:
            return
        # grow tables for the tick's ONE write per active slot (CoW'ing a
        # shared boundary page first — the first decode write after a
        # cache-hit admit is the canonical divergent write); a slot the
        # pool can't serve even after reclaim is evicted as overflow
        # (truncated output) so the rest of the batch keeps moving
        cow_pairs: List[tuple] = []
        for i in list(active):
            s = self.slots[i]
            if not (self._grow(i, s.cache_len + 1)
                    and self._cow_if_shared(i, s.cache_len, cow_pairs)):
                self._maybe_finish(i, completions, overflow=True)
                active.remove(i)
        if not active:
            return
        self._flush_cow(cow_pairs)
        S = self.cfg.max_seqs
        lens = np.zeros((S,), np.int32)
        last = np.zeros((S,), np.int32)
        act = np.zeros((S,), bool)
        seeds = np.zeros((S,), np.uint32)
        counts = np.zeros((S,), np.int32)
        for i in active:
            s = self.slots[i]
            lens[i] = s.cache_len
            last[i] = s.last_tok
            act[i] = True
            seeds[i] = s.req.seed
            counts[i] = len(s.gen)  # index of the token being sampled
        with journal.active().span("serve/decode_tick", batch=len(active)):
            rest = (self._device_tables(), jnp.asarray(lens),
                    jnp.asarray(last), jnp.asarray(act),
                    jnp.asarray(seeds), jnp.asarray(counts))
            self._guard("decode", rest)
            (toks, st), self.pages = self._decode_tick(
                self.params, self.pages, *rest)
            toks = np.asarray(toks)  # ONE host sync for the whole batch
            self._absorb_moe_stats(st)
        self.stats["decode_ticks"] += 1
        self.stats["decode_tokens"] += len(active)
        for i in active:
            s = self.slots[i]
            s.cache_len += 1
            s.last_tok = int(toks[i])
            s.gen.append(int(toks[i]))
            self._maybe_finish(i, completions)

    def _expire_deadlines(self, completions: List[Completion]) -> None:
        """Evict every request past its wall-clock deadline with the
        honest ``timeout`` status (partial output attached) — checked at
        the tick boundary BEFORE admit/decode, so an expired pending
        request never pays a prefill and an expired resident never pays
        another dispatch. Host-side clock reads only."""
        if not self._deadline_at:
            return
        now = self._now()
        jrnl = journal.active()
        keep: deque = deque()
        while self.pending:
            req = self.pending.popleft()
            at = self._deadline_at.get(req.req_id)
            if at is not None and now >= at:
                self._deadline_at.pop(req.req_id, None)
                self.stats["timeouts"] += 1
                jrnl.event("serve/timeout", req_id=str(req.req_id),
                           where="pending",
                           n_generated=len(req.committed))
                completions.append(Completion(
                    req.req_id, len(req.tokens), list(req.committed),
                    "timeout", timing=self._finish_timing(
                        req.req_id, "timeout")))
            else:
                keep.append(req)
        self.pending = keep
        for i, s in enumerate(self.slots):
            if s is None:
                continue
            at = self._deadline_at.get(s.req.req_id)
            if at is not None and now >= at:
                self._maybe_finish(i, completions, timeout=True)

    def step(self) -> List[Completion]:
        """One engine tick: expire deadlines, admit/prefill under the
        fairness cap, then one decode dispatch over the rolling batch.
        Returns the requests that finished this tick."""
        completions: List[Completion] = []
        self.stats["ticks"] += 1
        self._expire_deadlines(completions)
        with journal.active().span("serve/admit",
                                   pending=len(self.pending)):
            self._admit(completions)
        if self.metrics is not None:
            # per-token decode interval = the decode dispatch's wall time
            # over however many tokens it committed (1/slot plain, up to
            # k+1/slot speculative) — host clock reads only, the
            # dispatch itself is untouched
            t0 = self._now()
            tok0 = self.stats["decode_tokens"]
        if self._speculator is not None:
            self._speculator.decode_tick(completions)
        else:
            self._decode(completions)
        if self.metrics is not None:
            made = self.stats["decode_tokens"] - tok0
            if made > 0:
                self.metrics.on_decode_tick(
                    (self._now() - t0) * 1e3 / made, made)
            self.metrics.set_gauges(**self._gauge_snapshot())
            if self.metrics.maybe_drain(self.stats["ticks"]) is not None:
                # the SAME counters the bench banks, at the same cadence
                # the sketches drain — crash bundles and run_analyze
                # --serve read these, not a private in-memory dict
                journal.active().event("serve_stats", **self.stats)
        return completions

    def _gauge_snapshot(self) -> Dict[str, float]:
        """Live gauges for the metrics drain — every value is already a
        host scalar (queue/slot/table bookkeeping and stats counters);
        nothing here may touch a device buffer (the DLT001 rule)."""
        g = {"queue_depth": len(self.pending),
             "active_slots": sum(s is not None for s in self.slots),
             "pages_allocated": self.tables.pages_allocated,
             "free_blocks": self.tables.free_blocks,
             "evictions": self.stats["evictions"],
             "timeouts": self.stats["timeouts"]}
        if self.prefix is not None:
            hits, disp = self.stats["prefix_hits"], max(
                self.stats["prefill_dispatches"], 1)
            g["prefix_hit_rate"] = hits / disp
            g["cow_copies"] = self.stats["cow_copies"]
        if "spec_proposed" in self.stats:
            g["spec_accept_rate"] = (
                self.stats["spec_accepted"]
                / max(self.stats["spec_proposed"], 1))
        return g

    # ---------------------------------------------------------- the driver
    def run(self, requests: List[Request],
            arrivals: Optional[Dict[Any, int]] = None,
            max_ticks: int = 100_000) -> Dict[Any, Completion]:
        """Drain a workload: ``arrivals`` maps req_id → engine tick at
        which the request becomes visible (default: all at tick 0) — the
        staggered-arrival harness the continuous-batching tests drive."""
        arrivals = arrivals or {}
        todo = sorted(requests, key=lambda r: arrivals.get(r.req_id, 0))
        out: Dict[Any, Completion] = {}
        tick = 0
        while todo or self.has_work():
            while todo and arrivals.get(todo[0].req_id, 0) <= tick:
                self.submit(todo.pop(0))
            for c in self.step():
                out[c.req_id] = c
            tick += 1
            if tick > max_ticks:
                raise RuntimeError(
                    f"serving engine did not drain within {max_ticks} ticks "
                    f"({len(self.pending)} pending, "
                    f"{sum(s is not None for s in self.slots)} active)")
        return out
