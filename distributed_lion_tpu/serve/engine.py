"""Continuous-batching inference engine over the paged KV cache.

The serving counterpart of ``train/loop.py`` (ROADMAP item 4): requests
join a rolling batch on arrival, leave on EOS/length/overflow, and every
tick is ONE device dispatch — either a bucketed prefill or a decode step
over all active slots. The host's only per-tick work is table math
(serve/kv_cache.py) and reading back the tick's sampled tokens as one
array; there is no per-token host sync inside a tick (graft-check DLT001
pins the forbidden shape, tests/fixtures/analysis/serve/).

Scheduling (the vLLM recipe, simplified to two tick kinds):

- **admit** — pending requests take a free slot while pages fit, subject
  to a fairness cap on prefill tokens per engine tick
  (``prefill_cap_tokens``): a burst of long prompts cannot starve the
  decode batch for more than one tick.
- **prefill** — one dispatch per admitted request at a power-of-two
  bucketed length (a handful of compiles total, never per-prompt), tail
  masked via the scatter's ``valid`` lanes; samples the request's first
  token inside the same dispatch.
- **decode tick** — one dispatch advancing EVERY active slot one token:
  block-table decode (``*_decode_paged``) + per-slot sampling. Per-slot
  PRNG keys are ``fold_in(key(request.seed), generated_index)`` — a
  request's sample stream depends only on the request, NOT on which slot
  it rides or who shares the batch, which is what makes a staggered
  continuous-batching run produce outputs identical to solo runs
  (tests/test_serve.py pins it).
- **evict** — EOS / ``max_new_tokens`` / cache-overflow slots free their
  pages; the block table row goes back to sentinel, so the next decode
  tick simply ignores the slot (no recompile, the shapes never changed).

With ``ServeConfig.speculate`` set, the decode tick is replaced by the
speculative draft/verify/commit round (serve/speculate.py): up to k
drafted tokens per slot ride ONE batched verify dispatch and the accepted
prefix commits to the block tables — outputs pinned identical to this
one-token tick (greedy bit-identical, sampled token-identical to the same
per-request stream), only the tokens-per-dispatch ratio changes.

NF4/int8 frozen-weight serving: ``quant='nf4'`` re-packs the dense
checkpoint through ``ops.quant.quantize_tree`` once at engine build; the
decode paths dequantize inside each matmul's producer fusion
(``maybe_dequant``), so a 7B checkpoint serves from ~0.5 byte/param of
HBM plus the page pool.

Journal spans (``serve/admit``, ``serve/prefill``, ``serve/decode_tick``,
``serve/evict``) ride the PR-7 run journal when one is installed
(train/journal.install), giving ``cli/run_analyze`` a per-tick timeline.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from distributed_lion_tpu.serve.kv_cache import (
    BlockTables,
    bucket_tokens,
    init_pages,
)
from distributed_lion_tpu.train import journal


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    max_seqs: int = 8            # rolling-batch width (decode slots)
    block_size: int = 16         # tokens per KV page
    max_blocks_per_seq: int = 8  # block-table width; per-seq cap =
    #                              block_size * max_blocks_per_seq tokens
    num_blocks: int = 0          # page-pool size; 0 = auto
    #                              (max_seqs * max_blocks_per_seq: no slot
    #                              can starve another at full occupancy)
    prefill_cap_tokens: int = 512  # fairness cap: max PADDED prefill
    #                              tokens admitted per engine tick (a
    #                              single over-cap prompt still admits
    #                              when the tick has admitted nothing —
    #                              caps must not livelock)
    max_new_tokens: int = 64     # per-request default budget
    temperature: float = 0.0     # 0 = greedy; sampling knobs are engine-
    top_k: Optional[int] = None  # static (one compiled tick), seeds are
    top_p: Optional[float] = None  # per-request
    quant: str = "none"          # none | nf4 | int8 frozen-weight serving
    eos_id: Optional[int] = None
    speculate: str = ""          # '' = one token per decode tick;
    # '<drafter>:<k>' (ngram:4 | draft:2 ...) arms speculative decode
    # (serve/speculate.py): the drafter proposes up to k tokens per slot,
    # one batched verify dispatch scores them against this engine's model
    # on the paged cache, and the accepted prefix commits to the block
    # tables (rejected-tail pages roll back exactly). Outputs are pinned
    # identical to the non-speculative engine — greedy bit-identical,
    # sampled token-identical to the same per-request PRNG stream — the
    # knob only changes tokens per dispatch. 'draft:<k>' additionally
    # needs ServingEngine(draft_model=...).

    def resolved_num_blocks(self) -> int:
        return self.num_blocks or self.max_seqs * self.max_blocks_per_seq


@dataclasses.dataclass
class Request:
    req_id: Any
    tokens: List[int]                    # prompt token ids (non-empty)
    max_new_tokens: Optional[int] = None  # None = engine default
    seed: int = 0


@dataclasses.dataclass
class Completion:
    req_id: Any
    prompt_len: int
    tokens: List[int]    # generated ids (EOS included when emitted)
    reason: str          # eos | length | overflow | rejected


@dataclasses.dataclass
class _Slot:
    req: Request
    budget: int          # max new tokens for this request
    cache_len: int       # tokens whose k/v are in the pages
    last_tok: int        # newest sampled token (not yet in the cache)
    gen: List[int] = dataclasses.field(default_factory=list)


class ServeModel:
    """Family adapter: the paged decode hook + cache geometry the engine
    needs, built from a (params, config) pair. ``decode_paged(params,
    tokens, pages, tables, pos, valid)`` must return ``(logits [B,S,V]
    f32, pages')`` — models/gpt2.gpt2_decode_paged and
    models/llama.llama_decode_paged are the two implementations."""

    def __init__(self, family: str, cfg: Any, params: Any,
                 decode_paged: Callable, n_layer: int, kv_heads: int,
                 head_dim: int, cache_dtype: Any,
                 max_positions: Optional[int] = None):
        self.family = family
        self.cfg = cfg
        self.params = params
        self.decode_paged = decode_paged
        self.n_layer = n_layer
        self.kv_heads = kv_heads
        self.head_dim = head_dim
        self.cache_dtype = cache_dtype
        # the model's position budget (gpt2: learned wpe rows; llama's
        # rope extrapolates but n_ctx is still the trained horizon) — the
        # engine refuses a page geometry that would silently alias/exceed
        self.max_positions = max_positions

    @staticmethod
    def for_gpt2(params: Any, cfg: Any) -> "ServeModel":
        from distributed_lion_tpu.models.gpt2 import gpt2_decode_paged

        if getattr(cfg, "moe_experts", 0) > 0:
            # a bucketed (right-padded) prefill would route pad tokens
            # through the experts' fixed-capacity buffers, displacing real
            # tokens a solo run keeps — silently breaking the engine's
            # bit-identity guarantees. Refuse until the MoE decode path
            # masks pads out of routing.
            raise ValueError(
                "MoE checkpoints are not supported by the paged serving "
                "engine yet (pad tokens would consume expert capacity in "
                "the bucketed prefill); serve a dense checkpoint or use "
                "single-shot run_generate")

        def decode(p, toks, pages, tables, pos, valid=None):
            return gpt2_decode_paged(p, toks, cfg, pages, tables, pos, valid)

        return ServeModel("gpt2", cfg, params, decode, cfg.n_layer,
                          cfg.n_head, cfg.head_dim, cfg.compute_dtype,
                          max_positions=cfg.n_ctx)

    @staticmethod
    def for_llama(params: Any, cfg: Any) -> "ServeModel":
        from distributed_lion_tpu.models.llama import llama_decode_paged

        def decode(p, toks, pages, tables, pos, valid=None):
            return llama_decode_paged(p, toks, cfg, pages, tables, pos, valid)

        return ServeModel("llama", cfg, params, decode, cfg.n_layer,
                          cfg.n_kv_head, cfg.head_dim, cfg.compute_dtype,
                          max_positions=cfg.n_ctx)


def weight_bytes(params: Any) -> int:
    """Actual storage bytes of a (possibly quantized) weight tree —
    QuantizedTensor leaves count packed codes + absmax scales, dense
    leaves their array bytes. The bench's NF4-vs-bf16 column."""
    import jax

    from distributed_lion_tpu.ops.quant import QuantizedTensor

    total = 0
    for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedTensor)):
        if isinstance(leaf, QuantizedTensor):
            total += leaf.codes.size * leaf.codes.dtype.itemsize
            total += leaf.absmax.size * leaf.absmax.dtype.itemsize
        else:
            total += leaf.size * leaf.dtype.itemsize
    return total


def _sample_rows(logits, seeds, counts, temperature: float,
                 top_k: Optional[int], top_p: Optional[float]):
    """[B, V] logits → [B] tokens with PER-ROW keys derived from
    (request seed, generated-token index) — slot- and batch-independent
    draws (see module doc). Greedy when ``temperature == 0``."""
    import jax
    import jax.numpy as jnp

    from distributed_lion_tpu.models.generate import filter_logits

    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    filtered = filter_logits(logits, temperature, top_k, top_p)
    keys = jax.vmap(
        lambda s, c: jax.random.fold_in(jax.random.key(s), c))(seeds, counts)
    return jax.vmap(jax.random.categorical)(keys, filtered)


class ServingEngine:
    """See module doc. Host-side driver: ``submit`` requests, call
    ``step()`` per tick (or ``run()`` to drain a workload), collect
    :class:`Completion`s."""

    def __init__(self, model: ServeModel, cfg: ServeConfig,
                 draft_model: Optional[ServeModel] = None):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.cfg = cfg
        params = model.params
        if cfg.quant not in ("none", "nf4", "int8"):
            raise ValueError(f"unknown quant mode {cfg.quant!r}")
        if cfg.quant != "none":
            from distributed_lion_tpu.ops.quant import quantize_tree

            params = quantize_tree(params, cfg.quant)
        self.params = params
        horizon = cfg.block_size * cfg.max_blocks_per_seq
        if model.max_positions is not None and horizon > model.max_positions:
            raise ValueError(
                f"page geometry allows {horizon} tokens/seq but the model's "
                f"position budget is {model.max_positions} (n_ctx); shrink "
                "--block_size/--max_blocks_per_seq — positions past the "
                "trained horizon would silently alias")
        self.tables = BlockTables(cfg.resolved_num_blocks(), cfg.block_size,
                                  cfg.max_seqs, cfg.max_blocks_per_seq)
        self.pages = init_pages(model.n_layer, cfg.resolved_num_blocks(),
                                cfg.block_size, model.kv_heads,
                                model.head_dim, model.cache_dtype)
        self.slots: List[Optional[_Slot]] = [None] * cfg.max_seqs
        self.pending: deque = deque()
        self.stats = {"ticks": 0, "decode_ticks": 0, "prefill_dispatches": 0,
                      "decode_tokens": 0, "prefill_tokens": 0,
                      "padded_prefill_tokens": 0, "evictions": 0}

        # page donation halves the pool's HBM traffic on TPU; the CPU
        # backend has no donation and would warn every tick
        donate = (1,) if jax.default_backend() != "cpu" else ()
        samp = (cfg.temperature, cfg.top_k, cfg.top_p)

        def decode_tick(params, pages, tables, lens, last, seeds, counts):
            logits, pages = model.decode_paged(params, last[:, None], pages,
                                               tables, lens)
            return _sample_rows(logits[:, -1], seeds, counts, *samp), pages

        def prefill(params, pages, tables, toks, length, seed, count):
            valid = jnp.arange(toks.shape[1])[None, :] < length
            pos = jnp.zeros((1,), jnp.int32)
            logits, pages = model.decode_paged(params, toks, pages, tables,
                                               pos, valid)
            last = jax.lax.dynamic_index_in_dim(logits[0], length - 1, 0,
                                                keepdims=False)
            tok = _sample_rows(last[None], seed[None], count[None], *samp)
            return tok[0], pages

        self._decode_tick = jax.jit(decode_tick, donate_argnums=donate)
        self._prefill = jax.jit(prefill, donate_argnums=donate)

        self._speculator = None
        if cfg.speculate:
            from distributed_lion_tpu.serve.speculate import build_speculator

            self._speculator = build_speculator(self, cfg.speculate,
                                                draft_model)

    # ------------------------------------------------------------- intake
    def submit(self, req: Request) -> None:
        self.pending.append(req)

    def has_work(self) -> bool:
        return bool(self.pending) or any(s is not None for s in self.slots)

    def _bucket(self, n: int) -> int:
        return bucket_tokens(n, self.cfg.block_size,
                             self.cfg.max_blocks_per_seq)

    # -------------------------------------------------------------- ticks
    def _admit(self, completions: List[Completion]) -> None:
        import jax.numpy as jnp

        budget = self.cfg.prefill_cap_tokens
        admitted = 0
        jrnl = journal.active()
        while self.pending:
            req = self.pending[0]
            L = len(req.tokens)
            if L == 0 or L > self.tables.max_tokens_per_seq - 1:
                # -1: a prompt must leave room for one decode write
                self.pending.popleft()
                completions.append(Completion(req.req_id, L, [], "rejected"))
                continue
            P = self._bucket(L)
            if admitted and P > budget:
                break  # fairness cap — but never starve an empty tick
            slot = self.tables.find_free_slot()
            if slot is None or not self.tables.grow(slot, L + 1):
                break  # no slot/pages: wait for evictions
            self.pending.popleft()
            with jrnl.span("serve/prefill", req_id=str(req.req_id),
                           prompt_len=L, padded=P, slot=slot):
                toks = np.zeros((1, P), np.int32)
                toks[0, :L] = req.tokens
                tok, self.pages = self._prefill(
                    self.params, self.pages,
                    jnp.asarray(self.tables.tables[slot:slot + 1]),
                    jnp.asarray(toks), jnp.int32(L),
                    jnp.uint32(req.seed), jnp.int32(0))
                first = int(tok)  # ONE host sync per prefill dispatch
            budget -= P
            admitted += 1
            self.stats["prefill_dispatches"] += 1
            self.stats["prefill_tokens"] += L
            self.stats["padded_prefill_tokens"] += P
            slot_state = _Slot(req=req, cache_len=L, last_tok=first,
                               budget=(req.max_new_tokens
                                       or self.cfg.max_new_tokens))
            slot_state.gen.append(first)
            self.slots[slot] = slot_state
            if self._speculator is not None:
                self._speculator.on_admit(slot, list(req.tokens))
            self._maybe_finish(slot, completions)

    def _maybe_finish(self, slot: int, completions: List[Completion],
                      overflow: bool = False) -> None:
        s = self.slots[slot]
        reason = None
        if overflow:
            reason = "overflow"
        elif self.cfg.eos_id is not None and s.gen and \
                s.gen[-1] == self.cfg.eos_id:
            reason = "eos"
        elif len(s.gen) >= s.budget:
            reason = "length"
        if reason is None:
            return
        with journal.active().span("serve/evict", req_id=str(s.req.req_id),
                                   slot=slot, reason=reason,
                                   n_generated=len(s.gen)):
            self.tables.free_slot(slot)
            self.slots[slot] = None
            self.stats["evictions"] += 1
            if self._speculator is not None:
                self._speculator.on_evict(slot)
        completions.append(
            Completion(s.req.req_id, len(s.req.tokens), list(s.gen), reason))

    def _decode(self, completions: List[Completion]) -> None:
        import jax.numpy as jnp

        active = [i for i, s in enumerate(self.slots) if s is not None]
        if not active:
            return
        # grow tables for the tick's ONE write per active slot; a slot the
        # pool can't grow is evicted as overflow (truncated output) so the
        # rest of the batch keeps moving
        for i in list(active):
            if not self.tables.grow(i, self.slots[i].cache_len + 1):
                self._maybe_finish(i, completions, overflow=True)
                active.remove(i)
        if not active:
            return
        S = self.cfg.max_seqs
        lens = np.zeros((S,), np.int32)
        last = np.zeros((S,), np.int32)
        seeds = np.zeros((S,), np.uint32)
        counts = np.zeros((S,), np.int32)
        for i in active:
            s = self.slots[i]
            lens[i] = s.cache_len
            last[i] = s.last_tok
            seeds[i] = s.req.seed
            counts[i] = len(s.gen)  # index of the token being sampled
        with journal.active().span("serve/decode_tick", batch=len(active)):
            toks, self.pages = self._decode_tick(
                self.params, self.pages, jnp.asarray(self.tables.tables),
                jnp.asarray(lens), jnp.asarray(last), jnp.asarray(seeds),
                jnp.asarray(counts))
            toks = np.asarray(toks)  # ONE host sync for the whole batch
        self.stats["decode_ticks"] += 1
        self.stats["decode_tokens"] += len(active)
        for i in active:
            s = self.slots[i]
            s.cache_len += 1
            s.last_tok = int(toks[i])
            s.gen.append(int(toks[i]))
            self._maybe_finish(i, completions)

    def step(self) -> List[Completion]:
        """One engine tick: admit/prefill under the fairness cap, then one
        decode dispatch over the rolling batch. Returns the requests that
        finished this tick."""
        completions: List[Completion] = []
        self.stats["ticks"] += 1
        with journal.active().span("serve/admit",
                                   pending=len(self.pending)):
            self._admit(completions)
        if self._speculator is not None:
            self._speculator.decode_tick(completions)
        else:
            self._decode(completions)
        return completions

    # ---------------------------------------------------------- the driver
    def run(self, requests: List[Request],
            arrivals: Optional[Dict[Any, int]] = None,
            max_ticks: int = 100_000) -> Dict[Any, Completion]:
        """Drain a workload: ``arrivals`` maps req_id → engine tick at
        which the request becomes visible (default: all at tick 0) — the
        staggered-arrival harness the continuous-batching tests drive."""
        arrivals = arrivals or {}
        todo = sorted(requests, key=lambda r: arrivals.get(r.req_id, 0))
        out: Dict[Any, Completion] = {}
        tick = 0
        while todo or self.has_work():
            while todo and arrivals.get(todo[0].req_id, 0) <= tick:
                self.submit(todo.pop(0))
            for c in self.step():
                out[c.req_id] = c
            tick += 1
            if tick > max_ticks:
                raise RuntimeError(
                    f"serving engine did not drain within {max_ticks} ticks "
                    f"({len(self.pending)} pending, "
                    f"{sum(s is not None for s in self.slots)} active)")
        return out
