"""Process-isolated serving replica: the child-side worker.

``python -m distributed_lion_tpu.serve.replica_worker`` is spawned once
per replica by :class:`serve.fleet_proc.ProcessReplica` and speaks the
length-prefixed JSON protocol over its stdin/stdout pipes (framing and
codecs live in fleet_proc — ONE definition for both ends). Protocol
stdout is dup'd away and fd 1 redirected to stderr before the engine
builds, so a stray library print can never corrupt the frame stream.

Builder specs (the ``build`` command's payload):

- ``{"kind": "gpt2_tiny", "init_seed": 0, "serve": {...}}`` — a
  deterministic tiny GPT-2 (``GPT2Config.tiny()`` + ``gpt2_init`` from
  the seed) over ``ServeConfig(**serve)``; what the fleet tests use, and
  why a killed-and-respawned replica is the SAME model: identical seed,
  identical weights, no checkpoint file needed.
- ``{"kind": "cli", "gen": {...}, "serve": {...}}`` — the full
  ``run_serve`` build surface (GenerateArguments + ServeArguments
  field dicts); the child loads the checkpoint itself, so N replica
  processes each own their weights (real process isolation — the price
  of surviving a real SIGKILL is not sharing an address space).

Per ``tick`` command the worker applies control ops (the
``--inject_serve`` path riding the transport), re-stamps wire deadlines
against its OWN monotonic clock, admits submits, steps the engine once,
and replies with completions + the RecoveryRecord shadow + stats. The
``kill_after_step`` control raises genuine mid-decode death: the engine
steps (the decode dispatch really runs, tokens are really sampled) and
the process SIGKILLs itself BEFORE the reply — from the parent's side,
a replica that did work and then vanished, which is exactly the window
the zero-token-loss migration guarantee must cover.

Orphan discipline: every read polls with a bounded window and EOF on
stdin means the parent is gone — the worker exits instead of lingering
as a zombie decode loop (and graft-check DLT012 holds: no unbounded
blocking reads in serve/).
"""

from __future__ import annotations

import os
import signal
import sys
import time


def _build_engine(builder: dict):
    """Builder spec → a fresh ServingEngine owned by THIS process."""
    kind = builder.get("kind")
    if kind == "gpt2_tiny":
        import jax

        from distributed_lion_tpu.models.gpt2 import GPT2Config, gpt2_init
        from distributed_lion_tpu.serve.engine import (
            ServeConfig,
            ServeModel,
            ServingEngine,
        )

        cfg = GPT2Config.tiny()
        params = gpt2_init(jax.random.key(int(builder.get("init_seed", 0))),
                           cfg)
        model = ServeModel.for_gpt2(params, cfg)
        return ServingEngine(model, ServeConfig(**builder.get("serve", {})))
    if kind == "cli":
        from distributed_lion_tpu.cli.run_generate import GenerateArguments
        from distributed_lion_tpu.cli.run_serve import (
            ServeArguments,
            build_engine,
        )

        gen_args = GenerateArguments(**builder.get("gen", {}))
        serve_args = ServeArguments(**builder.get("serve", {}))
        _, engine = build_engine(gen_args, serve_args)
        return engine
    raise ValueError(f"unknown replica builder kind {kind!r}")


def main(time_fn=time.monotonic, sleep_fn=time.sleep) -> int:
    # force CPU before jax imports (same discipline as every CLI); the
    # parent already set JAX_PLATFORMS in the child env, this is the
    # belt for a directly-invoked worker
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from distributed_lion_tpu.serve.fleet_proc import (
        completion_to_wire,
        read_frame_blocking,
        record_to_wire,
        request_from_wire,
        write_frame,
    )

    # protocol hygiene: keep the REAL stdout for frames, point fd 1 at
    # stderr so any stray print (jax warnings, user code) lands there
    proto = os.fdopen(os.dup(sys.stdout.fileno()), "wb")
    sys.stdout.flush()
    os.dup2(sys.stderr.fileno(), sys.stdout.fileno())
    in_fd = sys.stdin.fileno()
    rbuf = bytearray()

    hello = read_frame_blocking(in_fd, buf=rbuf)
    if hello is None or hello.get("cmd") != "build":
        return 1
    engine = _build_engine(hello["builder"])
    write_frame(proto, {"ok": True, "pid": os.getpid()})

    while True:
        msg = read_frame_blocking(in_fd, buf=rbuf)
        if msg is None:
            return 0   # parent hung up — an orphan must exit, not decode
        cmd = msg.get("cmd")
        if cmd == "exit":
            return 0
        if cmd == "chains":
            export = getattr(engine, "export_prefix_chains", None)
            write_frame(proto, {"chains": export() if export else []})
            continue
        if cmd != "tick":
            write_frame(proto, {"error": f"unknown cmd {cmd!r}"})
            continue
        kill_after_step = False
        for ctl in msg.get("controls", ()):
            op = ctl.get("op")
            if op == "kill_after_step":
                kill_after_step = True
            elif op == "die_now":
                os.kill(os.getpid(), signal.SIGKILL)
            elif op == "stall_ms":
                # straggler injection: the reply (= the heartbeat) is
                # late by this much; the engine itself is untouched
                sleep_fn(float(ctl.get("ms", 0)) / 1000.0)
            elif op == "drop_pending":
                engine.pending.clear()
        now = time_fn()
        for d in msg.get("submit", ()):
            req = request_from_wire(d)
            remaining = d.get("deadline_remaining_s")
            engine.submit(req, deadline_at=(
                now + float(remaining) if remaining is not None else None))
        completions = engine.step()
        if kill_after_step:
            # mid-decode death, for real: work happened, tokens were
            # sampled, and the reply never arrives — the parent sees EOF
            # and must recover every accepted token from its shadow
            os.kill(os.getpid(), signal.SIGKILL)
        now = time_fn()
        write_frame(proto, {
            "tick_seq": msg.get("tick_seq"),
            "completions": [completion_to_wire(c) for c in completions],
            "records": [record_to_wire(r, now)
                        for r in engine.export_records()],
            "stats": dict(engine.stats),
            "pending_ids": [r.req_id for r in engine.pending],
            "has_work": engine.has_work(),
        })


if __name__ == "__main__":
    sys.exit(main())
