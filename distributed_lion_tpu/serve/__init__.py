"""Serving subsystem: continuous batching over a paged KV cache.

The first net-new runtime beside the trainer (ROADMAP item 4): the
reference repo trains but never serves; this package decodes vote-Lion
checkpoints at production batch sizes on the same stack that trained them.

- ``kv_cache``  — host-side page allocator + block tables (pure table
  math; the device pool lives in ``ops.attention``'s paged primitives)
- ``engine``    — admission scheduler + prefill/decode tick loop
- ``speculate`` — speculative decode: draft/verify/commit on the paged
  cache, outputs pinned identical to the one-token tick
- ``api``       — request-file front end (offline mode for CI)
- ``replica_plane`` — elastic multi-replica fleet: replica lifecycle,
  live request migration from recovery records (token-identical by the
  pinned PRNG streams), the serve-side fault matrix
"""

from distributed_lion_tpu.serve.engine import (  # noqa: F401
    Completion,
    RecoveryRecord,
    Request,
    ServeConfig,
    ServeModel,
    ServingEngine,
)
from distributed_lion_tpu.serve.kv_cache import BlockTables, init_pages  # noqa: F401
