"""Elastic serving: replica lifecycle + live request migration.

PR 10's control plane (train/control_plane.py) taught *training* to lose
and regain workers mid-run; this module is the serving twin (ROADMAP item
2(d)): a host-side per-replica lifecycle

    healthy ──drain──▶ draining ──residents done──▶ departed
       ▲                                               │
       │ probe ticks ok                                │ replica_rejoin
    rejoining ◀────────(fresh engine, fresh page pool)─┘
       (a crash jumps healthy/draining → departed directly)

driving a :class:`ServingFleet` of N independent ``ServingEngine``s behind
ONE admission queue. The robustness core is **request migration**: after
every replica tick the fleet copies each unfinished request's
:class:`~distributed_lion_tpu.serve.engine.RecoveryRecord` (prompt +
committed tokens + seed + budget + deadline — the minimal resumption
state) into its own shadow map, so when a replica dies the fleet never
asks the dead engine anything. A survivor re-admits the record: the
engine prefills the committed history (suffix-only when ``prefix_cache``
covers a shared prefix — the two compose) and resumes the pinned
per-request PRNG stream at ``token_index = len(committed)``, which makes
the migrated output token-identical to the never-migrated run BY
CONSTRUCTION — greedy and sampled, with and without speculation
(tests/test_replica_plane.py pins the matrix; the same discipline the
paper's 1-bit vote wire applies to degraded training quorums).

Fault matrix (the ``serve`` registry schedule, ``--inject_serve`` /
``resilience.parse_serve_specs``, consumed at fleet-tick boundaries via
the same ``resilience.consume_due`` helper the membership schedule uses):

- ``replica_crash:<r>:<tick>`` — r's engine is discarded mid-decode; its
  residents and pending requests re-queue from the recovery shadow with
  ZERO accepted-token loss (the shadow refreshes every tick).
- ``replica_drain:<r>[:<tick>]`` — r stops admitting; its pending queue
  migrates immediately, residents finish in place; when empty r departs.
- ``slow_tick:<r>:<ms>`` — every tick of r pays <ms> extra. The
  tick-latency watch flags r (mean over a recent window vs the median of
  its peers) and NEW work routes around it; residents keep their slots.
- ``replica_rejoin:<r>:<tick>`` — a departed r re-enters with a FRESH
  engine and page pool (the factory) through a short ``rejoining``
  probation: new work prefers healthy replicas until the probe window
  elapses (the rejoiner still admits when it is the only survivor — a
  probation that strands the queue would be worse than none).
- ``replica_kill:<r>:<tick>`` — process replicas only
  (serve/fleet_proc): a control frame arms a REAL ``SIGKILL`` in r's
  child, delivered AFTER the engine steps (tokens truly sampled, the
  reply never sent — the hardest cut). The fleet sees pipe EOF
  (``ReplicaGone``) and runs the same crash path: declared dead, shadow
  migration, zero accepted-token loss.

Routing honors the serve/api ``prefix_group`` affinity tag: requests of
one group land on one replica (so its prefix cache actually accumulates
their shared pages), falling back to least-loaded among admitting,
non-slow replicas. Failures are never silent: each migration consumes one
unit of the per-request retry budget with exponential tick backoff, and a
request that exhausts the budget (or its wall-clock ``deadline_s``)
completes with the honest ``failed`` / ``timeout`` status, partial output
attached.

Journal events (ride the installed PR-7 run journal; ``cli/run_analyze``
renders them as the replica timeline beside the PR-10 membership
timeline): ``replica_left`` / ``replica_rejoined`` / ``replica_draining``
/ ``replica_slow`` (cause, tick, resident counts, alive/world) and
``request_migrated`` / ``request_failed`` (req_id, from/to replica,
committed count, attempt, cause, tick). Process replicas add the
heartbeat trail: tick replies ARE the heartbeats, so a reply slower
than the worker's ``heartbeat_timeout_s`` journals
``replica_heartbeat_missed`` (replica, misses, max_misses, tick) — the
outstanding tick stays armed — and ``heartbeat_max_misses`` consecutive
strikes journal ``replica_declared_dead`` (cause ``heartbeat_lost``,
misses) before the ordinary ``replica_left``; pipe EOF or a corrupt
frame declares immediately with cause ``process_died``. Fleet-restart
persistence (``state_dir``/``persist_every``, serve/fleet_state) adds
``fleet_state_saved`` / ``fleet_state_restored`` / ``fleet_state_corrupt``.

Layering: host-side list/dict math only — engines do all device work;
this module must stay free of jax imports at module scope (the fleet is
pure scheduling, like train/control_plane is pure deciding).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from distributed_lion_tpu.serve.engine import (
    Completion,
    RecoveryRecord,
    Request,
    ServingEngine,
)
from distributed_lion_tpu.serve.fleet_proc import HeartbeatMiss, ReplicaGone
from distributed_lion_tpu.serve.metrics import (
    RequestTimes, ServeMetrics, TickLatencyWindow)
from distributed_lion_tpu.train import journal, resilience

REPLICA_STATES = ("healthy", "draining", "departed", "rejoining")


@dataclasses.dataclass
class _Replica:
    engine: Optional[ServingEngine]
    state: str = "healthy"
    slow_ms: int = 0                 # armed slow_tick injection (ms/tick)
    slow: bool = False               # flagged by the tick-latency watch
    rejoined_at: int = -1            # fleet tick of the last rejoin
    admissions: int = 0              # requests routed here, lifetime
    assigned: set = dataclasses.field(default_factory=set)
    tick_ms: deque = dataclasses.field(
        default_factory=lambda: deque(maxlen=16))
    hb_misses: int = 0               # consecutive missed heartbeats
    #                                  (process replicas only; reset on
    #                                  every on-time tick reply)


@dataclasses.dataclass
class _QueueItem:
    req: Request
    not_before: int                  # earliest admissible fleet tick
    #                                  (exponential migration backoff)
    deadline_at: Optional[float]     # absolute monotonic stamp — set at
    #                                  FIRST submission, never reset
    cause: Optional[str] = None      # non-None = this entry is a
    from_replica: int = -1           # migration (journaled at re-route)
    attempt: int = 0
    orphaned_at: int = -1            # fleet tick the home replica died
    #                                  (the recovery-latency clock)


class ServingFleet:
    """N serving replicas behind one admission queue (see module doc).

    ``factory`` builds ONE fresh :class:`ServingEngine` per call — shared
    weights are the caller's concern (close over one loaded model); the
    page pool and block tables are per-replica and a rejoiner always gets
    new ones. Drive with :meth:`submit` + :meth:`step`, or :meth:`run`
    (the same workload signature as ``ServingEngine.run``, so
    ``serve/api.handle_requests`` serves through a fleet unchanged).
    """

    def __init__(self, factory: Callable[[], ServingEngine],
                 replicas: int = 2, max_retries: int = 2,
                 backoff_ticks: int = 1, slow_factor: float = 4.0,
                 slow_min_ticks: int = 4, rejoin_probe_ticks: int = 2,
                 record_latency: bool = False,
                 heartbeat_max_misses: int = 3,
                 state_dir: Optional[str] = None,
                 persist_every: int = 0,
                 time_fn: Callable[[], float] = time.monotonic):
        if replicas < 1:
            raise ValueError(f"need >= 1 replica, got {replicas}")
        if max_retries < 0 or backoff_ticks < 1:
            raise ValueError(
                f"need max_retries >= 0 and backoff_ticks >= 1, got "
                f"{max_retries}/{backoff_ticks}")
        self.factory = factory
        self.replicas = [_Replica(engine=factory())
                         for _ in range(replicas)]
        self.max_retries = int(max_retries)
        self.backoff_ticks = int(backoff_ticks)
        self.slow_factor = float(slow_factor)
        self.slow_min_ticks = int(slow_min_ticks)
        self.rejoin_probe_ticks = int(rejoin_probe_ticks)
        self.tick_no = 0
        self.queue: deque = deque()            # _QueueItem FIFO
        self._records: Dict[Any, RecoveryRecord] = {}   # the shadow
        self._attempts: Dict[Any, int] = {}
        self._home: Dict[str, int] = {}        # prefix_group -> replica
        self.migration_latency_ticks: List[int] = []
        # per-replica tick-latency diagnostic — BOUNDED: a recency window
        # of raw samples (exact percentiles for the bench) plus a
        # mergeable log-bin sketch for full-history queries
        # (serve/metrics.TickLatencyWindow). A soak no longer grows a
        # float per tick per replica forever; the watch itself reads the
        # engine-side _Replica.tick_ms window as before.
        self.tick_latency_log: Optional[Dict[int, TickLatencyWindow]] = (
            {i: TickLatencyWindow() for i in range(replicas)}
            if record_latency else None)
        # queue-domain request clocks (fleet ticks): the timing columns
        # for completions the fleet itself emits — queue-side timeouts
        # and retry-budget failures never touch an engine, and their
        # queue wait must not vanish from the response records
        self.times = RequestTimes()
        # the injectable clock (graft-check DLT011): deadline stamps AND
        # the per-replica tick-latency samples read it — a test can feed
        # a fake clock and drive timeouts/straggler detection without
        # real sleeps (monotonic fractional seconds; the latency math
        # only ever subtracts, so any monotonic source is exact)
        self._now = time_fn
        self.metrics_drain_every = 64
        # process-replica liveness policy: a replica (fleet_proc.
        # ProcessReplica) whose tick reply misses its heartbeat deadline
        # this many CONSECUTIVE times is declared dead, SIGKILLed, and
        # its requests migrate from the shadow (in-process engines never
        # miss — their step() is a plain call)
        self.heartbeat_max_misses = int(heartbeat_max_misses)
        # fleet-restart persistence (serve/fleet_state): every
        # ``persist_every`` ticks the recovery shadow + prefix chains
        # land in ``state_dir`` under a sha256 manifest; 0 = only at
        # explicit save_state() calls (e.g. drain)
        self.state_dir = state_dir
        self.persist_every = int(persist_every)
        self.stats = {"ticks": 0, "migrations": 0, "failed": 0,
                      "timeouts": 0, "replica_crashes": 0,
                      "replica_drains": 0, "replica_rejoins": 0,
                      "slow_detected": 0, "heartbeat_misses": 0,
                      "replicas_declared_dead": 0, "state_saves": 0}

    # ------------------------------------------------------------- state
    def alive(self) -> int:
        return sum(r.engine is not None for r in self.replicas)

    def lifecycle(self) -> List[str]:
        """Per-replica state names — the fleet's authoritative view (the
        serving twin of ControlPlane.lifecycle)."""
        return [r.state for r in self.replicas]

    def _admitting(self) -> List[int]:
        return [i for i, r in enumerate(self.replicas)
                if r.engine is not None
                and r.state in ("healthy", "rejoining")]

    def has_work(self) -> bool:
        return bool(self.queue) or any(
            r.engine is not None and r.engine.has_work()
            for r in self.replicas)

    # ------------------------------------------------------------ intake
    def submit(self, req: Request,
               deadline_at: Optional[float] = None) -> None:
        """Queue a request; the wall-clock deadline (if any) stamps NOW —
        migrations inherit the stamp, they never reset it. An explicit
        ``deadline_at`` overrides the fresh stamp (the engine's own
        submit contract — the fleet-restart path re-stamps persisted
        REMAINING budgets against the new process's clock)."""
        if deadline_at is None and req.deadline_s is not None:
            deadline_at = self._now() + float(req.deadline_s)
        self.times.submitted(req.req_id, self.tick_no)
        self.queue.append(_QueueItem(req=req, not_before=self.tick_no,
                                     deadline_at=deadline_at))

    # --------------------------------------------------- fault transitions
    def _event(self, name: str, **fields) -> None:
        journal.active().event(name, alive=self.alive(),
                               world=len(self.replicas), **fields)

    def _orphan(self, rid: Any, rep: int, tick: int, cause: str,
                completions: List[Completion], count_attempt: bool) -> None:
        """Re-queue one request from the recovery shadow (its replica is
        gone), spending retry budget when the move was a failure
        (``count_attempt``) and never when it is an administrative drain.
        Budget exhaustion completes the request as ``failed`` — loud,
        partial output attached."""
        rec = self._records.get(rid)
        if rec is None:  # completed this very tick: nothing to recover
            return
        attempt = self._attempts.get(rid, 0)
        if count_attempt:
            attempt += 1
            self._attempts[rid] = attempt
        if attempt > self.max_retries:
            self._records.pop(rid, None)
            self._attempts.pop(rid, None)
            self.stats["failed"] += 1
            self._event("request_failed", req_id=str(rid), tick=tick,
                        from_replica=rep, attempts=attempt, cause=cause,
                        committed=len(rec.committed))
            completions.append(Completion(
                rid, len(rec.tokens), list(rec.committed), "failed",
                timing=self.times.finished(rid, tick)))
            return
        backoff = (self.backoff_ticks * (2 ** max(attempt - 1, 0))
                   if count_attempt else 0)
        self.queue.append(_QueueItem(
            req=rec.to_request(), not_before=tick + backoff,
            deadline_at=rec.deadline_at, cause=cause, from_replica=rep,
            attempt=attempt, orphaned_at=tick))

    def _crash(self, r: int, tick: int, cause: str,
               completions: List[Completion]) -> None:
        rep = self.replicas[r]
        if rep.engine is None:
            return  # already gone; a second signal is not a transition
        residents = sorted(rep.assigned, key=str)
        engine = rep.engine
        rep.engine = None          # the engine (and its device state) dies
        closer = getattr(engine, "close", None)
        if closer is not None:
            # a process replica leaves a real OS process behind — SIGKILL
            # it so a "crashed" child can never keep decoding as a zombie
            closer(kill=True)
        rep.state = "departed"
        rep.slow = False
        rep.tick_ms.clear()
        self.stats["replica_crashes"] += 1
        self._event("replica_left", replica=r, tick=tick, cause=cause,
                    residents=len(residents))
        self._home = {g: h for g, h in self._home.items() if h != r}
        for rid in residents:      # deterministic order: sorted req_ids
            self._orphan(rid, r, tick, cause, completions,
                         count_attempt=True)
        rep.assigned = set()

    def _declare_dead(self, r: int, tick: int, cause: str,
                      completions: List[Completion]) -> None:
        """The heartbeat verdict: journal ``replica_declared_dead``, then
        take the ordinary crash path — handle close (SIGKILL the child if
        it still breathes) + shadow migration. One journal event pair per
        incident: N ``replica_heartbeat_missed`` strikes, one verdict."""
        self.stats["replicas_declared_dead"] += 1
        self._event("replica_declared_dead", replica=r, tick=tick,
                    cause=cause, misses=self.replicas[r].hb_misses)
        self._crash(r, tick, cause, completions)

    def _drain(self, r: int, tick: int,
               completions: List[Completion]) -> None:
        rep = self.replicas[r]
        if rep.engine is None or rep.state == "draining":
            return
        rep.state = "draining"
        self.stats["replica_drains"] += 1
        self._event("replica_draining", replica=r, tick=tick,
                    cause="injected_drain", residents=len(rep.assigned))
        self._home = {g: h for g, h in self._home.items() if h != r}
        # pending (un-prefilled) requests migrate NOW — they hold no
        # cache state here, so moving them costs nothing and frees the
        # drain to finish in resident-count ticks; residents finish in
        # place (their pages live here). No retry budget is spent: a
        # drain is administrative, not a failure.
        pend = list(rep.engine.pending)
        rep.engine.pending.clear()
        for req in pend:
            rep.assigned.discard(req.req_id)
            self._orphan(req.req_id, r, tick, "drain", completions,
                         count_attempt=False)

    def _rejoin(self, r: int, tick: int) -> None:
        rep = self.replicas[r]
        if rep.engine is not None or rep.state != "departed":
            return  # rejoining a replica that never left is undefined —
            # ignore it the way the control plane ignores the matching
            # worker_rejoin (loud refusal would kill a fleet over a
            # mis-ticked schedule entry that changes nothing)
        rep.engine = self.factory()       # fresh page pool by construction
        rep.state = "rejoining"
        rep.slow = False
        rep.slow_ms = 0
        rep.hb_misses = 0
        rep.rejoined_at = tick
        rep.tick_ms.clear()
        self.stats["replica_rejoins"] += 1
        self._event("replica_rejoined", replica=r, tick=tick,
                    cause="injected_rejoin",
                    probe_ticks=self.rejoin_probe_ticks)

    def _consume_faults(self, tick: int,
                        completions: List[Completion]) -> None:
        for kind, r, at, arg in resilience.consume_due("serve", tick):
            if not 0 <= int(r) < len(self.replicas):
                raise ValueError(
                    f"serve fault {kind}:{r} outside fleet of "
                    f"{len(self.replicas)} replicas")
            r = int(r)
            if kind == "replica_crash":
                self._crash(r, tick, "injected_crash", completions)
            elif kind == "replica_kill":
                # a REAL process death: arm SIGKILL inside the child's
                # next tick (mid-decode — work happens, the reply never
                # arrives); on an in-process engine, degrade to the
                # simulated crash the old path provided
                arm = getattr(self.replicas[r].engine, "arm_kill", None)
                if arm is not None:
                    arm()
                else:
                    self._crash(r, tick, "injected_kill", completions)
            elif kind == "replica_drain":
                self._drain(r, tick, completions)
            elif kind == "slow_tick":
                self.replicas[r].slow_ms = int(arg)
            else:  # replica_rejoin
                self._rejoin(r, tick)

    # ----------------------------------------------------------- routing
    def _pick_replica(self, req: Request) -> Optional[int]:
        admitting = self._admitting()
        if not admitting:
            return None
        # probation: new work PREFERS replicas that have finished their
        # probe window — a fresh rejoiner only admits when no healthy
        # replica exists (it must not strand the queue as sole survivor);
        # then route around detected-slow replicas whenever a non-slow
        # candidate exists (residents stay — their pages live there;
        # only NEW work avoids the slow box)
        healthy = [i for i in admitting
                   if self.replicas[i].state == "healthy"]
        pool = healthy or admitting
        fast = [i for i in pool if not self.replicas[i].slow]
        pool = fast or pool
        if req.prefix_group is not None:
            home = self._home.get(req.prefix_group)
            if home in pool:
                return home
        # least-loaded: fewest assigned requests, lowest index breaks ties
        target = min(pool, key=lambda i: (len(self.replicas[i].assigned), i))
        if req.prefix_group is not None:
            self._home[req.prefix_group] = target
        return target

    def _route(self, tick: int, completions: List[Completion]) -> None:
        now = self._now()
        later: deque = deque()
        while self.queue:
            item = self.queue.popleft()
            rid = item.req.req_id
            if item.deadline_at is not None and now >= item.deadline_at:
                self._records.pop(rid, None)
                self._attempts.pop(rid, None)
                self.stats["timeouts"] += 1
                self._event("request_timeout", req_id=str(rid), tick=tick,
                            committed=len(item.req.committed))
                completions.append(Completion(
                    rid, len(item.req.tokens), list(item.req.committed),
                    "timeout", timing=self.times.finished(rid, tick)))
                continue
            if item.not_before > tick:
                later.append(item)
                continue
            target = self._pick_replica(item.req)
            if target is None:
                later.append(item)   # no admitting replica: wait (a
                continue             # scheduled rejoin may restore one)
            rep = self.replicas[target]
            rep.engine.submit(item.req, deadline_at=item.deadline_at)
            rep.assigned.add(rid)
            rep.admissions += 1
            # shadow the request IMMEDIATELY: a crash before this
            # replica's first export must still recover it
            self._records[rid] = RecoveryRecord.from_request(
                item.req, item.req.committed, item.req.max_new_tokens,
                item.deadline_at)
            if item.cause is not None:
                self.stats["migrations"] += 1
                if item.orphaned_at >= 0:
                    self.migration_latency_ticks.append(
                        tick - item.orphaned_at)
                self._event("request_migrated", req_id=str(rid), tick=tick,
                            from_replica=item.from_replica,
                            to_replica=target, cause=item.cause,
                            attempt=item.attempt,
                            committed=len(item.req.committed),
                            latency_ticks=max(tick - item.orphaned_at, 0))
        self.queue = later

    # ------------------------------------------------------------- watch
    def _watch_slow(self, tick: int) -> None:
        """Flag replicas whose recent MEDIAN tick latency exceeds
        ``slow_factor`` × the median of their live peers' medians — pure
        host-side clock math over the measured window, so an injected
        ``slow_tick`` is DETECTED from the same signal a real straggler
        would produce. Medians, not means: every replica's window carries
        one-off spikes (the first tick's jit compile, a GC pause) that
        must neither flag a healthy replica nor mask a slow one. Un-flags
        when the latency returns to band."""
        meds = {}
        for i, rep in enumerate(self.replicas):
            if rep.engine is not None and \
                    len(rep.tick_ms) >= self.slow_min_ticks:
                window = sorted(rep.tick_ms)
                meds[i] = window[len(window) // 2]
        for i, m in meds.items():
            peers = sorted(v for j, v in meds.items() if j != i)
            if not peers:
                continue
            med = peers[len(peers) // 2]
            rep = self.replicas[i]
            if m > self.slow_factor * max(med, 1e-6):
                if not rep.slow:
                    rep.slow = True
                    self.stats["slow_detected"] += 1
                    self._event("replica_slow", replica=i, tick=tick,
                                median_tick_ms=round(m, 3),
                                peer_median_ms=round(med, 3))
            elif rep.slow:
                rep.slow = False

    # -------------------------------------------------------------- tick
    def step(self) -> List[Completion]:
        """One fleet tick: consume due faults, route the admission queue,
        step every live replica once (refreshing the recovery shadow from
        its host-side tables), watch tick latency, finish drains."""
        completions: List[Completion] = []
        tick = self.tick_no
        self.stats["ticks"] += 1
        self._consume_faults(tick, completions)
        self._route(tick, completions)
        for i, rep in enumerate(self.replicas):
            if rep.engine is None or not rep.engine.has_work():
                continue
            t0 = self._now()
            if rep.slow_ms:
                time.sleep(rep.slow_ms / 1e3)   # the injected straggler
            try:
                stepped = rep.engine.step()
            except HeartbeatMiss:
                # the tick reply is late, not necessarily dead: the tick
                # stays outstanding in the handle (a late reply is
                # consumed next round), the fleet counts the strike
                rep.hb_misses += 1
                self.stats["heartbeat_misses"] += 1
                self._event("replica_heartbeat_missed", replica=i,
                            tick=tick, misses=rep.hb_misses,
                            max_misses=self.heartbeat_max_misses)
                if rep.hb_misses >= self.heartbeat_max_misses:
                    self._declare_dead(i, tick, "heartbeat_lost",
                                       completions)
                continue
            except ReplicaGone:
                # EOF / corrupt stream: the process is unrecoverable —
                # no strike budget, straight to dead
                self._declare_dead(i, tick, "process_died", completions)
                continue
            rep.hb_misses = 0
            for c in stepped:
                rid = c.req_id
                rep.assigned.discard(rid)
                self._records.pop(rid, None)
                self._attempts.pop(rid, None)
                # retire the fleet-side clock (the record keeps the
                # serving engine's own timing — the honest one: it saw
                # the prefill/decode ticks, the fleet only saw routing)
                self.times.finished(rid, tick)
                if c.reason == "timeout":
                    # a resident/engine-side deadline miss must show on
                    # the replica timeline like a queue-side one — an
                    # incident report that omits it would read as if the
                    # deadline never fired
                    self.stats["timeouts"] += 1
                    self._event("request_timeout", req_id=str(rid),
                                tick=tick, replica=i,
                                committed=len(c.tokens))
                completions.append(c)
            ms = (self._now() - t0) * 1e3
            rep.tick_ms.append(ms)
            if self.tick_latency_log is not None:
                self.tick_latency_log[i].add(ms)
            # refresh the shadow from the replica's host-side state: what
            # the fleet holds here is what a crash NEXT tick can recover,
            # which is every token accepted up to and including this tick
            for rec in rep.engine.export_records():
                self._records[rec.req_id] = rec
        self._watch_slow(tick)
        for i, rep in enumerate(self.replicas):
            if rep.state == "draining" and rep.engine is not None \
                    and not rep.engine.has_work():
                rep.engine = None
                rep.state = "departed"
                self._event("replica_left", replica=i, tick=tick,
                            cause="drained", residents=0)
            elif rep.state == "rejoining" and \
                    tick - rep.rejoined_at >= self.rejoin_probe_ticks:
                rep.state = "healthy"
        if self.stats["ticks"] % self.metrics_drain_every == 0:
            # the fleet counters ride the journal at the same drain
            # cadence as the engine planes — crash bundles and
            # run_analyze --serve read the numbers the bench banks
            self._event("fleet_stats", tick=tick,
                        queue_depth=len(self.queue), **self.stats)
        if self.state_dir and self.persist_every \
                and self.stats["ticks"] % self.persist_every == 0:
            self.save_state()
        self.tick_no += 1
        return completions

    # ---------------------------------------------------- restart surface
    def export_records(self) -> List[RecoveryRecord]:
        """Every unfinished request the fleet knows about: the recovery
        shadow (routed requests, refreshed each tick) plus queue items
        not yet routed — the same surface ``ServingEngine.export_records``
        gives, so the socket server streams through either target and the
        persistence plane snapshots the WHOLE in-flight set."""
        recs = dict(self._records)
        for item in self.queue:
            if item.req.req_id not in recs:
                recs[item.req.req_id] = RecoveryRecord.from_request(
                    item.req, item.req.committed, item.req.max_new_tokens,
                    item.deadline_at)
        return list(recs.values())

    def export_chains(self) -> List[List[int]]:
        """The union of every live replica's prefix-cache chains (maximal
        cached token prefixes), deduped — what fleet-restart persistence
        banks so a new fleet warm-starts its page pools instead of cold
        prefilling the shared system prompts."""
        seen = set()
        for rep in self.replicas:
            if rep.engine is None:
                continue
            export = getattr(rep.engine, "export_prefix_chains", None) \
                or getattr(rep.engine, "export_chains", None)
            if export is None:
                continue
            for chain in export():
                if chain:
                    seen.add(tuple(int(t) for t in chain))
        return [list(k) for k in sorted(seen, key=lambda k: (len(k), k))]

    def save_state(self) -> Optional[str]:
        """Persist the recovery shadow + prefix chains to ``state_dir``
        (atomic tmp+rename under a sha256 manifest — serve/fleet_state).
        Returns the written state file path, or None when persistence is
        not configured. Called on the ``persist_every`` cadence and by
        the drain path; safe to call at any tick boundary."""
        if not self.state_dir:
            return None
        from distributed_lion_tpu.serve import fleet_state

        path = fleet_state.save_fleet_state(
            self.state_dir, self.export_records(), self.export_chains(),
            tick=self.tick_no, now=self._now())
        self.stats["state_saves"] += 1
        return path

    def close(self) -> None:
        """Tear down every live replica handle (process replicas get a
        clean exit request, then the SIGKILL backstop). In-process
        engines have nothing to release — getattr-guarded, same as the
        crash path."""
        for rep in self.replicas:
            engine, rep.engine = rep.engine, None
            if engine is not None:
                closer = getattr(engine, "close", None)
                if closer is not None:
                    closer(kill=False)
            rep.state = "departed"

    def metrics_snapshot(self) -> Optional[Dict[str, Any]]:
        """Fleet-level metrics aggregate: fold every LIVE replica's
        sketch plane into one (pure bin-count merges — raw samples never
        leave a replica) plus the fleet's own gauges. None when no live
        replica runs with metrics armed. A departed replica's sketches
        die with its engine — the fleet-side diagnostics that must
        survive a crash (tick_latency_log, migration/timeout counters)
        live on the fleet, not the engine."""
        agg = ServeMetrics(RequestTimes())
        merged = False
        for rep in self.replicas:
            if rep.engine is not None and rep.engine.metrics is not None:
                agg.merge_from(rep.engine.metrics)
                merged = True
        if not merged:
            return None
        agg.set_gauges(queue_depth=len(self.queue), alive=self.alive(),
                       migrations=self.stats["migrations"],
                       failed=self.stats["failed"],
                       timeouts=self.stats["timeouts"])
        return agg.snapshot()

    # ------------------------------------------------------------ driver
    def run(self, requests: List[Request],
            arrivals: Optional[Dict[Any, int]] = None,
            max_ticks: int = 100_000) -> Dict[Any, Completion]:
        """Drain a workload — the ``ServingEngine.run`` signature, so
        ``serve/api`` drives a fleet and a single engine identically."""
        arrivals = arrivals or {}
        todo = sorted(requests, key=lambda r: arrivals.get(r.req_id, 0))
        out: Dict[Any, Completion] = {}
        while todo or self.has_work():
            while todo and arrivals.get(todo[0].req_id, 0) <= self.tick_no:
                self.submit(todo.pop(0))
            if self.queue and not self._admitting() \
                    and not resilience.fault("serve"):
                raise RuntimeError(
                    f"serving fleet has {len(self.queue)} queued request(s) "
                    f"but no admitting replica (lifecycle "
                    f"{self.lifecycle()}) and no scheduled rejoin — "
                    "refusing to spin forever")
            for c in self.step():
                out[c.req_id] = c
            if self.tick_no > max_ticks:
                raise RuntimeError(
                    f"serving fleet did not drain within {max_ticks} ticks "
                    f"({len(self.queue)} queued, lifecycle "
                    f"{self.lifecycle()})")
        return out
