"""Request/response front end for the serving engine.

Offline request-file mode (the CI-friendly surface): a JSONL file of
requests in, a JSONL file of responses out — the same strict-JSON
discipline as every other artifact (scripts/validate_metrics.py). Each
request line:

    {"id": "r1", "prompt": "Hello", "max_new_tokens": 32, "seed": 0,
     "arrival_tick": 0, "prefix_group": "sys-v2", "deadline_s": 2.5}

``prompt`` (text, run through the tokenizer) or ``tokens`` (explicit ids)
— one of the two is required. ``arrival_tick`` staggers admission for
continuous-batching runs (default 0 = all at start). ``prefix_group`` is
an OPTIONAL routing/accounting tag for requests sharing a prompt prefix
(the ``--prefix_cache`` engine matches by tokens, so the tag never
changes what is shared — under ``--replicas`` the fleet additionally
routes one group to one replica, serve/replica_plane); when present it
must be a non-empty string — validated strictly, echoed on the response
line. ``deadline_s`` is an OPTIONAL wall-clock budget from submission;
when present it must be a positive finite number — validated strictly,
echoed on the response line — and an expired request completes with the
honest ``timeout`` reason (partial output attached), never silent loss.
Response lines carry the request id, the generated ids/text, and the
finish reason (``eos | length | overflow | rejected | timeout | failed``
— the last two from deadlines and the fleet's retry budget)::

    {"id": "r1", "text": "...", "tokens": [...], "reason": "eos",
     "prompt_len": 5, "n_generated": 12, "queue_ticks": 1,
     "ttft_ticks": 1, "decode_ticks": 11}

Timing columns come from the engine's always-on tick-domain request
clocks (serve/metrics.RequestTimes): ``queue_ticks`` on every terminal
status — including ``timeout``/``failed``/``overflow``, and including
queue-side deaths stamped by the fleet that never reached an engine —
``ttft_ticks``/``decode_ticks`` once a first token existed, and wall
``ttft_ms`` when the request was served with the metrics plane armed
(ServeConfig.metrics / --serve_metrics).

The live socket mode (serve/net.py, ``run_serve --listen``) rides the
same strict per-request validation through :func:`parse_request_obj` —
one schema, two transports; the offline mode is what CI and the decode
bench gate on.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from distributed_lion_tpu.serve.engine import Completion, Request


def parse_request_obj(d: dict, where: str, tokenizer=None,
                      default_id=None) -> Tuple[Request, int]:
    """One request object (a parsed JSONL line or a live socket frame) →
    ``(Request, arrival_tick)`` under the strict serve/api schema. The
    ONE validation site for both transports — a field the offline mode
    refuses must refuse identically over the wire (``where`` names the
    source for the error message: ``"reqs.jsonl:7"`` or
    ``"client 127.0.0.1:52710"``)."""
    rid = d.get("id", default_id)
    if rid is None:
        raise ValueError(f"{where}: request needs an 'id'")
    if "tokens" in d:
        toks = [int(t) for t in d["tokens"]]
    elif "prompt" in d and tokenizer is not None:
        toks = tokenizer.encode(d["prompt"], add_bos=False) or [0]
    else:
        raise ValueError(
            f"{where}: request needs 'tokens' or 'prompt' "
            "(with a tokenizer)")
    group = d.get("prefix_group")
    if group is not None and (
            not isinstance(group, str) or not group):
        # strict: a mistyped tag must fail loudly, not silently
        # ride as accounting noise (same discipline as every
        # other artifact field — scripts/validate_metrics.py)
        raise ValueError(
            f"{where}: 'prefix_group' must be a non-empty "
            f"string when present, got {group!r}")
    deadline = d.get("deadline_s")
    if deadline is not None and (
            isinstance(deadline, bool)
            or not isinstance(deadline, (int, float))
            or not deadline > 0 or deadline != deadline
            or deadline == float("inf")):
        # strict: a malformed deadline must refuse, not silently
        # serve without one (a request that LOOKS bounded but
        # isn't is the worst failure mode a deadline can have)
        raise ValueError(
            f"{where}: 'deadline_s' must be a positive finite "
            f"number when present, got {deadline!r}")
    req = Request(
        req_id=rid, tokens=list(toks),
        max_new_tokens=d.get("max_new_tokens"),
        seed=int(d.get("seed", 0)), prefix_group=group,
        deadline_s=(float(deadline) if deadline is not None else None))
    return req, int(d.get("arrival_tick", 0))


def load_request_file(path: str, tokenizer=None
                      ) -> Tuple[List[Request], Dict[Any, int]]:
    """Parse a request JSONL into engine requests + arrival schedule.
    Raises on a request with neither ``tokens`` nor (``prompt`` + a
    tokenizer) — a silently-dropped request must not look served."""
    requests: List[Request] = []
    arrivals: Dict[Any, int] = {}
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            req, at = parse_request_obj(d, f"{path}:{i}", tokenizer,
                                        default_id=f"req{i}")
            requests.append(req)
            arrivals[req.req_id] = at
    return requests, arrivals


def completion_record(c: Completion, tokenizer=None) -> dict:
    rec = {"id": c.req_id, "tokens": list(c.tokens), "reason": c.reason,
           "prompt_len": c.prompt_len, "n_generated": len(c.tokens)}
    if c.timing:
        # request-lifecycle clocks (serve/metrics.RequestTimes — stamped
        # by the engine, or by the fleet for queue-side deaths):
        # queue_ticks on EVERY terminal status, ttft_ticks/decode_ticks
        # once a first token existed, wall ttft_ms when the metrics
        # plane was armed. Validated strictly by validate_metrics.py's
        # responses schema, same discipline as prefix_group.
        for k in ("ttft_ticks", "queue_ticks", "decode_ticks"):
            if k in c.timing:
                rec[k] = int(c.timing[k])
        if "ttft_ms" in c.timing:
            rec["ttft_ms"] = float(c.timing["ttft_ms"])
    if tokenizer is not None:
        rec["text"] = tokenizer.decode([int(t) for t in c.tokens])
    return rec


def handle_requests(engine, requests: List[Request],
                    arrivals: Optional[Dict[Any, int]] = None,
                    tokenizer=None) -> List[dict]:
    """Drive an engine — or a ``serve/replica_plane.ServingFleet``, the
    two share the ``run(requests, arrivals)`` surface — over a workload;
    response records in request order (an unserved id would be loudly
    missing, not silently skipped). Requests tagged with ``prefix_group``
    / ``deadline_s`` get them echoed on the record."""
    done = engine.run(requests, arrivals or {})
    records = []
    for r in requests:
        rec = completion_record(done[r.req_id], tokenizer)
        if r.prefix_group is not None:
            rec["prefix_group"] = r.prefix_group
        if r.deadline_s is not None:
            rec["deadline_s"] = r.deadline_s
        records.append(rec)
    return records


def serve_request_file(engine, in_path: str, out_path: str,
                       tokenizer=None) -> List[dict]:
    requests, arrivals = load_request_file(in_path, tokenizer)
    records = handle_requests(engine, requests, arrivals, tokenizer)
    with open(out_path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, allow_nan=False) + "\n")
    return records
