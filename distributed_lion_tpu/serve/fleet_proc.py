"""Process-isolated serving replicas: the parent-side transport.

ROADMAP item 2(b): today's :class:`serve.replica_plane.ServingFleet`
replica is a same-process Python object, so "crash" is a method call.
This module makes replica failure a real OS event: each replica is a
``python -m distributed_lion_tpu.serve.replica_worker`` subprocess
speaking a length-prefixed JSON protocol over its stdin/stdout pipes,
and :class:`ProcessReplica` is the parent-side handle that exposes the
exact duck surface the fleet already drives engines through —
``submit`` / ``step`` / ``export_records`` / ``has_work`` / ``pending``
/ ``stats`` — so the fleet's routing, recovery-shadow, and migration
machinery run UNCHANGED across the process boundary.

Wire protocol (one 4-byte big-endian length prefix + UTF-8 strict JSON
per frame):

- parent → child: ``{"cmd": "build", "builder": {...}}`` once, then
  ``{"cmd": "tick", "tick_seq": n, "submit": [...], "controls": [...]}``
  per fleet tick (at most ONE outstanding tick — the reply is the
  heartbeat), plus ``{"cmd": "chains"}`` (persistence cadence) and
  ``{"cmd": "exit"}``.
- child → parent: ``{"ok": true, "pid": p}`` after build, then per tick
  ``{"tick_seq": n, "completions": [...], "records": [...], "stats": {...},
  "pending_ids": [...], "has_work": b}``.

Heartbeats ARE the tick replies: a reply not arriving within
``heartbeat_timeout_s`` raises :class:`HeartbeatMiss` (the fleet
journals ``replica_heartbeat_missed`` and retries with the SAME
outstanding tick — a slow child's late reply is consumed on the next
poll, never lost); ``heartbeat_max_misses`` consecutive misses — or an
EOF/broken pipe (:class:`ReplicaGone`) — gets the replica declared
dead, SIGKILLed, and its requests migrated from the fleet's recovery
shadow exactly as the in-process crash path pins (token-identical by
construction: the shadow holds prompt + committed + seed, and the
per-request PRNG stream resumes at ``len(committed)``).

Wall-clock deadlines never cross the boundary as absolute stamps — the
two processes have different monotonic epochs — they travel as
REMAINING seconds and re-stamp against the receiver's clock.

Layering: stdlib-only at module scope (no jax — the child imports jax,
the parent never does on this path), every read behind a ``selectors``
poll with an explicit deadline (graft-check DLT012), and every clock
read through the injectable ``time_fn`` seam (DLT011).
"""

from __future__ import annotations

import json
import os
import selectors
import signal
import struct
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional

from distributed_lion_tpu.serve.engine import (
    Completion,
    RecoveryRecord,
    Request,
)

_HEADER = struct.Struct(">I")
MAX_FRAME_BYTES = 64 << 20   # a torn length prefix must not OOM the host

WORKER_MODULE = "distributed_lion_tpu.serve.replica_worker"


class HeartbeatMiss(RuntimeError):
    """The outstanding tick's reply missed its heartbeat deadline. The
    child may be slow, not dead — the caller decides after
    ``heartbeat_max_misses`` strikes; the outstanding tick stays armed
    and a late reply is consumed by the next read."""


class ReplicaGone(RuntimeError):
    """The pipe is closed or the frame stream is corrupt: the replica
    process is unrecoverable (exited, SIGKILLed, or garbled)."""


# ------------------------------------------------------------------- framing
def write_frame(fobj, obj: dict) -> None:
    """One length-prefixed strict-JSON frame. ``flush`` per frame — a
    buffered half-frame on a dying sender must never look like silence
    followed by garbage on the receiver."""
    payload = json.dumps(obj, allow_nan=False).encode("utf-8")
    fobj.write(_HEADER.pack(len(payload)) + payload)
    fobj.flush()


def read_frame_blocking(fd: int, poll_s: float = 60.0,
                        buf: Optional[bytearray] = None) -> Optional[dict]:
    """Child-side frame read: poll ``fd`` in bounded ``poll_s`` windows
    (never an unbounded block — the DLT012 discipline) until one full
    frame arrives or EOF (returns None — the parent died or hung up, and
    an orphaned worker must exit, not linger)."""
    buf = bytearray() if buf is None else buf
    sel = selectors.DefaultSelector()
    sel.register(fd, selectors.EVENT_READ)
    try:
        while True:
            frame = _take_frame(buf)
            if frame is not None:
                return frame
            if not sel.select(poll_s):
                continue   # re-poll: idle parents are legal, orphans EOF
            chunk = os.read(fd, 1 << 16)
            if not chunk:
                return None
            buf += chunk
    finally:
        sel.close()


def _take_frame(buf: bytearray) -> Optional[dict]:
    if len(buf) < _HEADER.size:
        return None
    (n,) = _HEADER.unpack(bytes(buf[:_HEADER.size]))
    if n > MAX_FRAME_BYTES:
        raise ReplicaGone(f"frame length {n} exceeds {MAX_FRAME_BYTES} — "
                          "corrupt stream")
    if len(buf) < _HEADER.size + n:
        return None
    payload = bytes(buf[_HEADER.size:_HEADER.size + n])
    del buf[:_HEADER.size + n]
    try:
        return json.loads(payload)
    except ValueError as e:
        raise ReplicaGone(f"corrupt frame payload: {e}") from e


# --------------------------------------------------------------- wire codecs
def request_to_wire(req: Request, deadline_remaining_s: Optional[float]
                    ) -> dict:
    d = {"req_id": req.req_id, "tokens": [int(t) for t in req.tokens],
         "seed": int(req.seed),
         "committed": [int(t) for t in req.committed]}
    if req.max_new_tokens is not None:
        d["max_new_tokens"] = int(req.max_new_tokens)
    if req.prefix_group is not None:
        d["prefix_group"] = req.prefix_group
    if deadline_remaining_s is not None:
        d["deadline_remaining_s"] = float(deadline_remaining_s)
    return d


def request_from_wire(d: dict) -> Request:
    return Request(req_id=d["req_id"], tokens=list(d["tokens"]),
                   max_new_tokens=d.get("max_new_tokens"),
                   seed=int(d.get("seed", 0)),
                   prefix_group=d.get("prefix_group"),
                   committed=list(d.get("committed", ())))


def record_to_wire(rec: RecoveryRecord, now: float) -> dict:
    d = {"req_id": rec.req_id, "tokens": [int(t) for t in rec.tokens],
         "committed": [int(t) for t in rec.committed],
         "seed": int(rec.seed)}
    if rec.budget is not None:
        d["budget"] = int(rec.budget)
    if rec.prefix_group is not None:
        d["prefix_group"] = rec.prefix_group
    if rec.deadline_at is not None:
        # absolute monotonic stamps are meaningless across processes —
        # ship the REMAINING budget, re-stamp on the receiving clock
        d["deadline_remaining_s"] = float(rec.deadline_at - now)
    return d


def record_from_wire(d: dict, now: float) -> RecoveryRecord:
    remaining = d.get("deadline_remaining_s")
    return RecoveryRecord(
        req_id=d["req_id"], tokens=list(d["tokens"]),
        committed=list(d["committed"]), seed=int(d["seed"]),
        budget=d.get("budget"), prefix_group=d.get("prefix_group"),
        deadline_at=(now + float(remaining) if remaining is not None
                     else None))


def completion_to_wire(c: Completion) -> dict:
    return {"req_id": c.req_id, "prompt_len": int(c.prompt_len),
            "tokens": [int(t) for t in c.tokens], "reason": c.reason,
            "timing": c.timing}


def completion_from_wire(d: dict) -> Completion:
    return Completion(d["req_id"], int(d["prompt_len"]),
                      list(d["tokens"]), d["reason"],
                      timing=d.get("timing"))


# ------------------------------------------------------------ pending mirror
class _PendingMirror(list):
    """The fleet drains a replica by ``list(engine.pending)`` +
    ``engine.pending.clear()``. For a process replica the authoritative
    pending queue lives in the child; this mirror tracks it from tick
    replies, and ``clear()`` also schedules a ``drop_pending`` control
    so the child parts with those requests before its next admission."""

    def __init__(self, owner: "ProcessReplica"):
        super().__init__()
        self._owner = owner

    def clear(self) -> None:   # type: ignore[override]
        if self:
            self._owner._queue_control({"op": "drop_pending"})
        super().clear()


class ProcessReplica:
    """One serving replica in its own OS process (see module doc).

    Duck-compatible with the slice of :class:`ServingEngine` the fleet
    touches. ``metrics`` is None — a process replica's sketch plane
    cannot be merged parent-side without shipping raw bins every tick;
    its request timings still ride the completion records."""

    def __init__(self, builder: dict, heartbeat_timeout_s: float = 60.0,
                 spawn_timeout_s: float = 600.0,
                 label: str = "", env: Optional[dict] = None,
                 time_fn: Callable[[], float] = time.monotonic):
        self.builder = builder
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.label = label
        self._now = time_fn
        self.metrics = None
        self.stats: Dict[str, Any] = {}
        self.pending = _PendingMirror(self)
        self._known: Dict[Any, Request] = {}
        self._records: List[RecoveryRecord] = []
        self._submits: List[tuple] = []
        self._controls: List[dict] = []
        self._outstanding: Optional[int] = None    # seq of the armed tick
        self._seq = 0
        self._has_work = False
        self._rbuf = bytearray()
        self._dead = False
        child_env = dict(os.environ)
        child_env.setdefault("JAX_PLATFORMS", "cpu")
        # token-identical across the boundary requires the child to
        # sample with the parent's PRNG layout: mirror jax config the
        # parent set PROGRAMMATICALLY (env vars already inherit) into
        # the child's env. sys.modules keeps this module jax-free — the
        # parent only has a config to mirror if it imported jax itself.
        parent_jax = sys.modules.get("jax")
        if parent_jax is not None:
            for opt in ("jax_threefry_partitionable", "jax_enable_x64"):
                try:
                    val = bool(getattr(parent_jax.config, opt))
                except AttributeError:
                    continue
                child_env.setdefault(opt.upper(), "1" if val else "0")
        if env:
            child_env.update(env)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", WORKER_MODULE],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=None,
            env=child_env)
        write_frame(self.proc.stdin, {"cmd": "build", "builder": builder})
        hello = self._read_reply(spawn_timeout_s,
                                 miss_ok=False)  # build may jit-compile
        if not (isinstance(hello, dict) and hello.get("ok")):
            self.close(kill=True)
            raise ReplicaGone(
                f"replica worker failed to build: {hello!r}")
        self.pid = int(hello["pid"])

    # ----------------------------------------------------------- transport
    def _read_reply(self, timeout_s: float, miss_ok: bool = True) -> dict:
        """One frame from the child within ``timeout_s`` — the heartbeat
        read. Timeout raises :class:`HeartbeatMiss` (the partial buffer
        is KEPT: a frame split across misses reassembles, never tears);
        EOF or stream corruption raises :class:`ReplicaGone`."""
        if self._dead:
            raise ReplicaGone("replica already closed")
        fd = self.proc.stdout.fileno()
        deadline = self._now() + float(timeout_s)
        sel = selectors.DefaultSelector()
        sel.register(fd, selectors.EVENT_READ)
        try:
            while True:
                frame = _take_frame(self._rbuf)
                if frame is not None:
                    return frame
                left = deadline - self._now()
                if left <= 0:
                    if miss_ok:
                        raise HeartbeatMiss(
                            f"no reply within {timeout_s}s")
                    raise ReplicaGone(
                        f"no build reply within {timeout_s}s")
                if not sel.select(min(left, 1.0)):
                    continue
                chunk = os.read(fd, 1 << 16)
                if not chunk:
                    raise ReplicaGone("replica pipe closed (EOF)")
                self._rbuf += chunk
        finally:
            sel.close()

    def _queue_control(self, ctl: dict) -> None:
        self._controls.append(ctl)

    # -------------------------------------------- the engine duck surface
    def submit(self, req: Request, deadline_at: Optional[float] = None
               ) -> None:
        self._submits.append((req, deadline_at))
        self._known[req.req_id] = req
        self.pending.append(req)
        self._has_work = True

    def has_work(self) -> bool:
        return (self._outstanding is not None or self._has_work
                or bool(self._submits) or bool(self._controls))

    def export_records(self) -> List[RecoveryRecord]:
        return list(self._records)

    def step(self) -> List[Completion]:
        """One replica tick across the boundary. Sends the tick command
        (buffered submits + controls) unless one is already outstanding
        from a missed heartbeat, then reads the reply under the
        heartbeat deadline. Raises HeartbeatMiss / ReplicaGone — the
        fleet owns the miss-count / declare-dead policy."""
        if self._dead:
            raise ReplicaGone("replica already closed")
        if self._outstanding is None:
            now = self._now()
            msg = {"cmd": "tick", "tick_seq": self._seq, "controls":
                   list(self._controls), "submit": []}
            for req, deadline_at in self._submits:
                remaining = (deadline_at - now
                             if deadline_at is not None else None)
                if remaining is None and req.deadline_s is not None:
                    remaining = float(req.deadline_s)
                msg["submit"].append(request_to_wire(req, remaining))
            self._submits.clear()
            self._controls.clear()
            try:
                write_frame(self.proc.stdin, msg)
            except (BrokenPipeError, OSError) as e:
                raise ReplicaGone(f"replica pipe closed: {e}") from e
            self._outstanding = self._seq
            self._seq += 1
        reply = self._read_reply(self.heartbeat_timeout_s)
        if reply.get("tick_seq") != self._outstanding:
            raise ReplicaGone(
                f"tick reply out of sequence: got {reply.get('tick_seq')}, "
                f"expected {self._outstanding}")
        self._outstanding = None
        now = self._now()
        self._records = [record_from_wire(d, now)
                         for d in reply.get("records", ())]
        self.stats = dict(reply.get("stats", ()))
        self._has_work = bool(reply.get("has_work"))
        completions = [completion_from_wire(d)
                       for d in reply.get("completions", ())]
        for c in completions:
            self._known.pop(c.req_id, None)
        pend_ids = set(reply.get("pending_ids", ()))
        super(_PendingMirror, self.pending).clear()
        self.pending.extend(self._known[r] for r in pend_ids
                            if r in self._known)
        return completions

    # --------------------------------------------------- control / faults
    def arm_kill(self) -> None:
        """Arm a real SIGKILL inside the child's NEXT tick: the worker
        steps its engine (the decode dispatch runs) and dies before the
        reply — the mid-decode process death the acceptance matrix
        pins. The parent observes EOF on the heartbeat read."""
        self._queue_control({"op": "kill_after_step"})

    def stall_next_tick(self, ms: int) -> None:
        """Make the child sleep ``ms`` before replying to its next tick
        (the cross-process straggler / heartbeat-miss injection)."""
        self._queue_control({"op": "stall_ms", "ms": int(ms)})

    def export_chains(self, timeout_s: Optional[float] = None
                      ) -> List[dict]:
        """Synchronous chain export for the persistence cadence. Never
        called with a tick outstanding (the fleet persists after a
        completed tick); a miss returns [] — persistence must degrade,
        not kill a slow replica."""
        if self._dead or self._outstanding is not None:
            return []
        try:
            write_frame(self.proc.stdin, {"cmd": "chains"})
            reply = self._read_reply(timeout_s or self.heartbeat_timeout_s)
            return list(reply.get("chains", ()))
        except (HeartbeatMiss, ReplicaGone, OSError):
            return []

    def close(self, kill: bool = False) -> None:
        """Tear the replica down. ``kill=True`` is the crash path (the
        ``--inject_serve replica_crash`` control message + SIGKILL
        backstop); ``kill=False`` asks for a clean exit first."""
        if self._dead:
            return
        self._dead = True
        try:
            write_frame(self.proc.stdin, {"cmd": "exit",
                                          "hard": bool(kill)})
        except (BrokenPipeError, OSError):
            pass
        if kill and self.proc.poll() is None:
            try:
                self.proc.send_signal(signal.SIGKILL)
            except (ProcessLookupError, OSError):
                pass
        try:
            self.proc.stdin.close()
        except OSError:
            pass
        try:
            # reap with a bounded wait; SIGKILL as the backstop so close
            # can never hang the fleet on a wedged child
            self.proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            try:
                self.proc.send_signal(signal.SIGKILL)
            except (ProcessLookupError, OSError):
                pass
            self.proc.wait(timeout=5.0)
        try:
            self.proc.stdout.close()
        except OSError:
            pass


def process_replica_factory(builder: dict,
                            heartbeat_timeout_s: float = 60.0,
                            spawn_timeout_s: float = 600.0,
                            time_fn: Callable[[], float] = time.monotonic
                            ) -> Callable[[], ProcessReplica]:
    """A fleet ``factory`` spawning one worker process per call — what
    ``ServingFleet(factory, ...)`` needs for process isolation (a
    rejoining replica gets a FRESH process, page pool included)."""
    def factory() -> ProcessReplica:
        return ProcessReplica(builder,
                              heartbeat_timeout_s=heartbeat_timeout_s,
                              spawn_timeout_s=spawn_timeout_s,
                              time_fn=time_fn)
    return factory
