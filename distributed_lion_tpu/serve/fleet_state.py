"""Fleet-restart persistence: the serving plane's crash-safe state file.

ROADMAP item 2(c), the last layer of the process-isolated serving PR:
the :class:`~distributed_lion_tpu.serve.replica_plane.ServingFleet`'s
recovery shadow (every in-flight request's prompt + committed + seed +
remaining deadline) and the union of its replicas' PrefixCache chains
(the maximal shared-prefix token runs) persist to a state directory on a
cadence and at drain, so a FULL fleet stop — deploy, host reboot,
``kill -9`` of the parent itself — is recoverable:

- in-flight requests resume token-identically by construction
  (``run_serve --resume_fleet`` re-submits each record; the engine
  re-prefills prompt + committed and resumes the pinned per-request PRNG
  stream at ``len(committed)`` — the PR 14 migration path, pointed at a
  file instead of a live shadow);
- the page pool warm-starts: each persisted chain re-prefills ONCE as a
  1-token priming request before the restored requests submit, so their
  shared system prompts prefix-hit instead of cold prefilling per
  request (prefill tokens saved is measured and asserted by the bench).

Integrity rides the PR 3 checkpoint idioms exactly: every state file is
written tmp+rename (a torn write can never shadow a good file), digested
into ``manifest.json`` (itself tmp+rename), and verified sha256 + size
at load — a corrupt or truncated file is journaled
(``fleet_state_corrupt``) and SKIPPED loudly, falling back to the
previous generation, never silently dropping requests.

Wall-clock deadlines persist as REMAINING seconds (``deadline_remaining_s``
— the fleet_proc wire codec, reused verbatim): absolute monotonic stamps
do not survive a process, let alone a reboot. A deadline that expired
while the fleet was down restores already-expired and completes with the
honest ``timeout`` status on the first routing pass.

Stdlib-only, host-side (no jax); every clock value is passed IN by the
caller (``now=``) — this module never reads a clock (DLT011's seam
discipline, one level stricter: no seam needed when there is no read).
"""

from __future__ import annotations

import json
import os
import pathlib
from typing import Any, Dict, List, Optional

from distributed_lion_tpu.serve.engine import RecoveryRecord, Request
from distributed_lion_tpu.serve.fleet_proc import (
    record_from_wire,
    record_to_wire,
)
from distributed_lion_tpu.train import journal
from distributed_lion_tpu.train.resilience import MANIFEST, sha256_file

STATE_FORMAT = 1
STATE_PREFIX = "fleet-"


def _atomic_write(path: pathlib.Path, data: bytes) -> None:
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_bytes(data)
    os.replace(tmp, path)


def _infer_group(chain: List[int],
                 records: List[RecoveryRecord]) -> Optional[str]:
    """A chain's routing tag: the ``prefix_group`` of any in-flight
    request whose prompt extends the chain. Persisted so the restart's
    priming request lands on the SAME replica the restored group will
    route to (affinity is what makes the warm pages reachable)."""
    n = len(chain)
    for rec in records:
        if rec.prefix_group is not None and len(rec.tokens) >= n \
                and [int(t) for t in rec.tokens[:n]] == chain:
            return rec.prefix_group
    return None


def save_fleet_state(state_dir: str, records: List[RecoveryRecord],
                     chains: List[List[int]], tick: int, now: float,
                     keep: int = 2) -> str:
    """One persistence generation: ``fleet-<tick>.json`` (tmp+rename) +
    a refreshed sha256 manifest, pruning to the newest ``keep``
    generations. Returns the state file path. ``now`` is the caller's
    monotonic clock — deadlines convert to remaining seconds against
    it."""
    sdir = pathlib.Path(state_dir)
    sdir.mkdir(parents=True, exist_ok=True)
    name = f"{STATE_PREFIX}{int(tick):08d}.json"
    payload = {
        "format": STATE_FORMAT, "tick": int(tick),
        "records": [record_to_wire(r, now) for r in records],
        "chains": [{"tokens": [int(t) for t in c],
                    "group": _infer_group([int(t) for t in c], records)}
                   for c in chains],
    }
    raw = json.dumps(payload, sort_keys=True, allow_nan=False).encode()
    _atomic_write(sdir / name, raw)
    # prune BEFORE the manifest refresh so the manifest never lists a
    # file the prune just deleted
    states = sorted(p.name for p in sdir.glob(f"{STATE_PREFIX}*.json"))
    for old in states[:-keep] if keep > 0 else []:
        try:
            (sdir / old).unlink()
        except OSError:
            pass
        states = [s for s in states if s != old]
    files = {s: {"sha256": sha256_file(sdir / s),
                 "bytes": (sdir / s).stat().st_size}
             for s in states}
    man = json.dumps({"format": STATE_FORMAT, "files": files},
                     sort_keys=True, allow_nan=False).encode()
    _atomic_write(sdir / MANIFEST, man)
    journal.active().event("fleet_state_saved", tick=int(tick),
                           records=len(records), chains=len(chains),
                           path=str(sdir / name))
    return str(sdir / name)


def load_fleet_state(state_dir: str, now: float) -> Dict[str, Any]:
    """Newest VALID persisted generation: verify size + sha256 against
    the manifest, parse, and re-stamp deadlines against ``now``. A
    failing generation journals ``fleet_state_corrupt`` and falls back
    to the previous one — requests are never silently dropped; when no
    generation survives, raise (the caller asked to resume and there is
    nothing honest to resume from)."""
    sdir = pathlib.Path(state_dir)
    man_path = sdir / MANIFEST
    if not man_path.is_file():
        raise FileNotFoundError(
            f"--resume_fleet: no {MANIFEST} in {state_dir} (was the "
            "fleet started with --fleet_state_dir?)")
    try:
        man = json.loads(man_path.read_text())
        files = man["files"]
    except (ValueError, KeyError) as e:
        raise ValueError(
            f"--resume_fleet: corrupt manifest {man_path}: {e}") from e
    for name in sorted(files, reverse=True):   # newest generation first
        path = sdir / name
        why = None
        try:
            meta = files[name]
            if not path.is_file():
                why = "missing"
            elif path.stat().st_size != int(meta["bytes"]):
                why = (f"size {path.stat().st_size} != manifest "
                       f"{meta['bytes']} (torn write)")
            elif sha256_file(path) != meta["sha256"]:
                why = "sha256 mismatch (corrupt)"
        except (OSError, KeyError, ValueError, TypeError) as e:
            why = f"unreadable: {e}"
        if why is None:
            try:
                payload = json.loads(path.read_text())
                if payload.get("format") != STATE_FORMAT:
                    raise ValueError(
                        f"format {payload.get('format')!r} != "
                        f"{STATE_FORMAT}")
                state = {
                    "tick": int(payload["tick"]),
                    "records": [record_from_wire(d, now)
                                for d in payload["records"]],
                    "chains": [{"tokens": [int(t) for t in c["tokens"]],
                                "group": c.get("group")}
                               for c in payload["chains"]],
                    "path": str(path),
                }
            except (ValueError, KeyError, TypeError) as e:
                why = f"invalid payload: {e}"
            else:
                journal.active().event(
                    "fleet_state_restored", path=str(path),
                    tick=state["tick"], records=len(state["records"]),
                    chains=len(state["chains"]))
                return state
        journal.active().event("fleet_state_corrupt", path=str(path),
                               reason=why)
    raise ValueError(
        f"--resume_fleet: no valid fleet state in {state_dir} (every "
        "generation failed manifest verification — see "
        "fleet_state_corrupt journal events)")


def resume_into(target, state: Dict[str, Any]) -> Dict[str, int]:
    """Restore a loaded state into a fresh engine/fleet ``target``:
    warm-start the page pool by running each persisted chain as a
    1-token priming request (re-prefills the shared prefix ONCE and —
    with ``prefix_cache`` on — banks its pages; the priming request's
    ``prefix_group`` pins the fleet's group→replica home so restored
    requests land where the warm pages live), then re-submit every
    in-flight record with its surviving deadline. The caller drives the
    target afterwards (``run``/``step``) — restoration queues work, it
    does not serve it."""
    primers = []
    for i, ch in enumerate(state["chains"]):
        toks = list(ch["tokens"])
        if not toks:
            continue
        primers.append(Request(req_id=f"__warm{i}", tokens=toks,
                               max_new_tokens=1, seed=0,
                               prefix_group=ch.get("group")))
    if primers:
        target.run(primers, {})
    for rec in state["records"]:
        target.submit(rec.to_request(), deadline_at=rec.deadline_at)
    return {"restored": len(state["records"]),
            "chains_primed": len(primers), "tick": state["tick"]}
