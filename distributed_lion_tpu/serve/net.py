"""Streaming socket front end for the serving stack (stdlib-only).

The production face of ROADMAP item 2(a): a single-threaded,
selectors-based accept loop over the serve/api core — the SAME engine /
fleet tick loop CI drives offline, now fed by live connections:

- **Requests in**: newline-delimited JSON, the exact offline schema
  (:func:`serve.api.parse_request_obj` is the one validation site for
  both transports — a field the file mode refuses, the wire refuses
  identically). ``prefix_group`` rides through to the fleet's affinity
  routing unchanged.
- **Frames out**: newline-delimited strict JSON (``allow_nan=False``),
  one of ``accepted`` / ``tokens`` / ``done`` / ``reject`` / ``error``.
  Token frames are emitted at the HOST tick boundary by diffing the
  engine's per-tick ``export_records()`` committed lists — host bytes
  the recovery shadow already pays for, so streaming adds ZERO
  per-token device syncs (the same no-new-sync discipline as the
  metrics plane).
- **Backpressure is honest**: when the admission queue or the page pool
  is tight, a new request gets an explicit ``reject`` frame carrying
  ``retry_after_s`` instead of unbounded server-side buffering. The
  reference client (:class:`ServeClient`) retries with exponential
  backoff on top of the server's hint; :func:`drive_open_loop` is the
  open-loop driver ``scripts/workload_gen.py --stream`` and the bench's
  socket-soak leg share.

Timeout discipline (graft-check DLT012): every potentially-blocking
socket/pipe operation here runs behind a ``selectors`` poll with an
explicit timeout or a ``settimeout`` deadline — a serve-plane host loop
must never be able to hang forever on a peer that went away.

Layering: stdlib only at module scope (no jax, no numpy) — the server
drives engines through the same duck surface the fleet uses
(``submit`` / ``step`` / ``has_work`` / ``export_records``), so crash
tooling and the workload generator import this module on boxes with no
accelerator stack.
"""

from __future__ import annotations

import json
import selectors
import socket
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from distributed_lion_tpu.serve import api as serve_api
from distributed_lion_tpu.train import journal


def encode_request(d: dict) -> bytes:
    """Canonical wire bytes for one request object: sorted keys, compact
    separators, strict JSON, one trailing newline. Byte-identical across
    reruns for the same dict — the determinism `workload_gen --stream`
    pins (the request STREAM is a pure function of the generator seed)."""
    return (json.dumps(d, sort_keys=True, separators=(",", ":"),
                       allow_nan=False) + "\n").encode("utf-8")


def encode_frame(d: dict) -> bytes:
    return (json.dumps(d, allow_nan=False) + "\n").encode("utf-8")


class _Conn:
    """Per-connection state: receive buffer, send buffer, owned request
    ids, and the per-request committed-token counts already streamed."""

    __slots__ = ("sock", "peer", "rbuf", "wbuf", "reqs", "sent", "seq")

    def __init__(self, sock: socket.socket, peer: str):
        self.sock = sock
        self.peer = peer
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.reqs: set = set()
        self.sent: Dict[Any, int] = {}   # req_id -> committed tokens sent
        self.seq = 0                     # lines parsed (error locations)


class ServeServer:
    """Single-threaded streaming server over one engine or fleet.

    ``target`` is anything with the engine tick surface: ``submit(req)``,
    ``step() -> completions``, ``has_work()``, ``export_records()`` —
    a :class:`~distributed_lion_tpu.serve.engine.ServingEngine` or a
    :class:`~distributed_lion_tpu.serve.replica_plane.ServingFleet`
    (process-isolated or not) both qualify. The loop interleaves socket
    polling with engine ticks: poll (zero timeout while the engine has
    work, ``idle_poll_s`` otherwise), admit complete request lines,
    tick, stream the tick's new tokens, flush.

    Backpressure knobs: ``max_queue_depth`` bounds the admission queue
    (engine ``pending`` / fleet ``queue``); ``min_free_blocks`` keeps a
    page-pool floor (single-engine targets only — a fleet's pools are
    per-replica and its admission queue is the pressure signal). A
    request arriving over either limit is rejected with an explicit
    ``retry_after_s`` frame, never buffered unboundedly.
    """

    def __init__(self, target, host: str = "127.0.0.1", port: int = 0,
                 tokenizer=None, max_queue_depth: int = 32,
                 min_free_blocks: int = 0, retry_after_s: float = 0.05,
                 idle_poll_s: float = 0.005,
                 time_fn: Callable[[], float] = time.monotonic):
        self.target = target
        self.tokenizer = tokenizer
        self.max_queue_depth = int(max_queue_depth)
        self.min_free_blocks = int(min_free_blocks)
        self.retry_after_s = float(retry_after_s)
        self.idle_poll_s = float(idle_poll_s)
        self._now = time_fn
        self.stop = False
        self.stats = {"accepted": 0, "rejected": 0, "completed": 0,
                      "bad_lines": 0, "conns": 0, "client_gone": 0,
                      "ticks": 0}
        self._conns: Dict[int, _Conn] = {}       # fd -> conn
        self._owner: Dict[Any, _Conn] = {}       # req_id -> conn
        self.sel = selectors.DefaultSelector()
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, int(port)))
        self._lsock.listen(64)
        self._lsock.setblocking(False)
        self.sel.register(self._lsock, selectors.EVENT_READ, None)
        self.addr = self._lsock.getsockname()
        journal.active().event("serve_listening", host=self.addr[0],
                               port=int(self.addr[1]))

    # -------------------------------------------------------------- pressure
    def _queue_depth(self) -> int:
        q = getattr(self.target, "queue", None)       # fleet admission queue
        if q is None:
            q = getattr(self.target, "pending", ())   # engine pending deque
        return len(q)

    def _tight(self) -> bool:
        if self._queue_depth() >= self.max_queue_depth:
            return True
        tables = getattr(self.target, "tables", None)
        if tables is not None and self.min_free_blocks > 0:
            return tables.free_blocks < self.min_free_blocks
        return False

    # ------------------------------------------------------------------- I/O
    def _accept(self) -> None:
        while True:
            try:
                sock, peer = self._lsock.accept()
            except BlockingIOError:
                return
            sock.setblocking(False)
            conn = _Conn(sock, f"{peer[0]}:{peer[1]}")
            self._conns[sock.fileno()] = conn
            self.sel.register(sock, selectors.EVENT_READ, conn)
            self.stats["conns"] += 1

    def _drop(self, conn: _Conn, *, gone: bool = False) -> None:
        """Close one connection. In-flight requests KEEP running — their
        tokens are simply no longer streamed anywhere (the journal gets
        a loud ``client_gone`` so dropped streams are visible)."""
        if gone and conn.reqs:
            self.stats["client_gone"] += 1
            journal.active().event("client_gone", peer=conn.peer,
                                   inflight=len(conn.reqs))
        for rid in conn.reqs:
            self._owner.pop(rid, None)
        conn.reqs.clear()
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        self._conns.pop(conn.sock.fileno(), None)
        conn.sock.close()

    def _send(self, conn: _Conn, frame: dict) -> None:
        conn.wbuf += encode_frame(frame)

    def _flush(self, conn: _Conn) -> None:
        while conn.wbuf:
            try:
                n = conn.sock.send(conn.wbuf)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._drop(conn, gone=True)
                return
            if n <= 0:
                return
            del conn.wbuf[:n]

    def _handle_line(self, conn: _Conn, line: bytes) -> None:
        conn.seq += 1
        where = f"client {conn.peer}:{conn.seq}"
        try:
            d = json.loads(line)
            if not isinstance(d, dict):
                raise ValueError(f"{where}: request must be a JSON object")
        except ValueError as e:
            self.stats["bad_lines"] += 1
            self._send(conn, {"event": "error", "error": str(e)})
            return
        if self._tight():
            # honest backpressure: an explicit machine-readable reject
            # the client can back off on — never unbounded buffering
            self.stats["rejected"] += 1
            self._send(conn, {"id": d.get("id"), "event": "reject",
                              "retry_after_s": self.retry_after_s})
            return
        try:
            req, _ = serve_api.parse_request_obj(d, where, self.tokenizer)
        except (ValueError, TypeError) as e:
            self.stats["bad_lines"] += 1
            self._send(conn, {"id": d.get("id"), "event": "error",
                              "error": str(e)})
            return
        if req.req_id in self._owner:
            self._send(conn, {"id": req.req_id, "event": "error",
                              "error": f"{where}: duplicate in-flight "
                                       f"request id {req.req_id!r}"})
            return
        self.target.submit(req)
        conn.reqs.add(req.req_id)
        conn.sent[req.req_id] = 0
        self._owner[req.req_id] = conn
        self.stats["accepted"] += 1
        self._send(conn, {"id": req.req_id, "event": "accepted"})

    def poll_io(self, timeout: float) -> None:
        """One poll pass with an explicit timeout (the DLT012 seam):
        accept ready connections, read ready sockets, dispatch complete
        request lines, flush pending output."""
        for key, _ in self.sel.select(timeout):
            if key.data is None:
                self._accept()
                continue
            conn: _Conn = key.data
            try:
                chunk = conn.sock.recv(65536)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError:
                self._drop(conn, gone=True)
                continue
            if not chunk:
                self._drop(conn, gone=bool(conn.reqs))
                continue
            conn.rbuf += chunk
            while True:
                nl = conn.rbuf.find(b"\n")
                if nl < 0:
                    break
                line = bytes(conn.rbuf[:nl]).strip()
                del conn.rbuf[:nl + 1]
                if line:
                    self._handle_line(conn, line)
        for conn in list(self._conns.values()):
            self._flush(conn)

    # ------------------------------------------------------------- streaming
    def _stream_progress(self) -> None:
        """Diff the recovery-shadow committed lists against what each
        connection has already been sent — pure host list slicing on
        records the tick loop exports anyway (zero new device syncs)."""
        for rec in self.target.export_records():
            conn = self._owner.get(rec.req_id)
            if conn is None:
                continue
            n = len(rec.committed)
            prev = conn.sent.get(rec.req_id, 0)
            if n > prev:
                self._send(conn, {"id": rec.req_id, "event": "tokens",
                                  "tokens": [int(t) for t in
                                             rec.committed[prev:]],
                                  "n": n})
                conn.sent[rec.req_id] = n

    def _finish(self, completions) -> None:
        for c in completions:
            self.stats["completed"] += 1
            conn = self._owner.pop(c.req_id, None)
            if conn is None:
                continue
            prev = conn.sent.pop(c.req_id, 0)
            conn.reqs.discard(c.req_id)
            if len(c.tokens) > prev:
                self._send(conn, {"id": c.req_id, "event": "tokens",
                                  "tokens": [int(t) for t in
                                             c.tokens[prev:]],
                                  "n": len(c.tokens)})
            rec = serve_api.completion_record(c, self.tokenizer)
            rec["event"] = "done"
            self._send(conn, rec)

    # ------------------------------------------------------------ the driver
    def serve_tick(self) -> int:
        """One interleaved unit: poll sockets, tick the engine if it has
        work, stream the tick's progress. Returns completions count."""
        self.poll_io(0.0 if self.target.has_work() else self.idle_poll_s)
        if not self.target.has_work():
            return 0
        completions = self.target.step()
        self.stats["ticks"] += 1
        self._stream_progress()
        self._finish(completions)
        for conn in list(self._conns.values()):
            self._flush(conn)
        return len(completions)

    def run(self, stop_when: Optional[Callable[[], bool]] = None,
            max_wall_s: Optional[float] = None) -> None:
        """Serve until ``self.stop`` is set, ``stop_when()`` returns
        True, or ``max_wall_s`` elapses (a hard deadline so a test or a
        soak can never hang the host loop forever)."""
        t_end = (self._now() + float(max_wall_s)
                 if max_wall_s is not None else None)
        while not self.stop:
            self.serve_tick()
            if stop_when is not None and stop_when():
                return
            if t_end is not None and self._now() >= t_end:
                return

    def close(self) -> None:
        for conn in list(self._conns.values()):
            self._drop(conn)
        try:
            self.sel.unregister(self._lsock)
        except (KeyError, ValueError):
            pass
        self._lsock.close()
        self.sel.close()


# --------------------------------------------------------------------- client
class ServeClient:
    """Small reference client: one request per call, streaming frames
    collected into the final response record, explicit-reject retry with
    exponential backoff on top of the server's ``retry_after_s`` hint.
    Every socket op runs under ``settimeout(timeout_s)`` — the client
    honors the same no-indefinite-block discipline as the server."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0,
                 max_retries: int = 8, backoff_base_s: float = 0.02,
                 sleep_fn: Callable[[float], None] = time.sleep):
        self.addr = (host, int(port))
        self.timeout_s = float(timeout_s)
        self.max_retries = int(max_retries)
        self.backoff_base_s = float(backoff_base_s)
        self._sleep = sleep_fn
        self.retries = 0
        self.rejects = 0

    @staticmethod
    def _read_frames(sock: socket.socket):
        """Yield frames until a terminal one arrives. Reads ride the
        socket's ``settimeout`` deadline — a dead server raises
        ``socket.timeout`` instead of hanging the caller forever."""
        f = sock.makefile("rb")
        try:
            while True:
                line = f.readline()          # bounded by sock's settimeout
                if not line:
                    raise ConnectionError("server closed the connection")
                frame = json.loads(line)
                yield frame
                if frame.get("event") in ("done", "reject", "error"):
                    return
        finally:
            f.close()

    def request(self, req: dict, on_tokens=None) -> dict:
        """Send one request dict (serve/api schema); returns the final
        response record. ``on_tokens(list)`` observes each streaming
        frame's delta. Raises RuntimeError after the retry budget."""
        payload = encode_request(req)
        last = None
        for attempt in range(self.max_retries + 1):
            sock = socket.create_connection(self.addr,
                                            timeout=self.timeout_s)
            sock.settimeout(self.timeout_s)
            try:
                sock.sendall(payload)
                tokens: List[int] = []
                for frame in self._read_frames(sock):
                    ev = frame.get("event")
                    if ev == "tokens":
                        tokens.extend(int(t) for t in frame["tokens"])
                        if on_tokens is not None:
                            on_tokens(frame["tokens"])
                    elif ev == "done":
                        return frame
                    elif ev == "reject":
                        self.rejects += 1
                        last = frame
                        break
                    elif ev == "error":
                        raise RuntimeError(
                            f"server refused request: {frame.get('error')}")
            finally:
                sock.close()
            # rejected: back off (server hint, then exponential) and retry
            self.retries += 1
            hint = float(last.get("retry_after_s", 0.0)) if last else 0.0
            self._sleep(max(hint, self.backoff_base_s * (2 ** attempt)))
        raise RuntimeError(
            f"request {req.get('id')!r} rejected {self.rejects} times — "
            f"retry budget ({self.max_retries}) exhausted")


def drive_open_loop(host: str, port: int, records: List[dict],
                    tick_s: float = 0.0, timeout_s: float = 60.0,
                    max_wall_s: float = 600.0,
                    retry_backoff_s: float = 0.02,
                    time_fn: Callable[[], float] = time.monotonic
                    ) -> Dict[str, Any]:
    """Open-loop socket driver over ONE multiplexed connection: each
    request record is sent at ``arrival_tick * tick_s`` after start
    (open loop: the schedule never waits for responses), frames are
    demultiplexed by id, rejects re-arm with backoff. Returns
    ``{"responses": {id: record}, "rejects": n, "retries": n,
    "wall_s": s}``. The FIRST-attempt payload byte sequence is a pure
    function of ``records`` (:func:`encode_request`), which is what
    ``workload_gen --stream`` pins as byte-identical across reruns."""
    payloads = {r.get("id"): encode_request(r) for r in records}
    sends = deque(
        (float(r.get("arrival_tick", 0)) * tick_s, payloads[r.get("id")],
         r.get("id"), 0) for r in records)
    attempts: Dict[Any, int] = {}
    want = {r.get("id") for r in records}
    responses: Dict[Any, dict] = {}
    rejects = retries = 0
    sock = socket.create_connection((host, int(port)), timeout=timeout_s)
    sock.setblocking(False)
    sel = selectors.DefaultSelector()
    sel.register(sock, selectors.EVENT_READ, None)
    rbuf = bytearray()
    t0 = time_fn()
    deadline = t0 + float(max_wall_s)
    try:
        while len(responses) < len(want):
            now = time_fn()
            if now >= deadline:
                raise TimeoutError(
                    f"open-loop drive incomplete after {max_wall_s}s: "
                    f"{len(responses)}/{len(want)} responses")
            # paced sends whose time has come (open loop: send-time is
            # schedule-driven, never response-driven). Retries re-enter
            # the deque out of order, so scan rather than assume sorted.
            keep: deque = deque()
            while sends:
                due, payload, rid, attempt = sends.popleft()
                if due > now - t0:
                    keep.append((due, payload, rid, attempt))
                    continue
                try:
                    sock.sendall(payload)
                except BlockingIOError:
                    keep.append((due, payload, rid, attempt))
            sends = keep
            next_due = min((d for d, _, _, _ in sends),
                           default=now - t0 + 0.05) - (now - t0)
            for _key, _ev in sel.select(max(min(next_due, 0.05), 0.0)):
                chunk = sock.recv(65536)
                if not chunk:
                    raise ConnectionError("server closed mid-drive")
                rbuf += chunk
            while True:
                nl = rbuf.find(b"\n")
                if nl < 0:
                    break
                frame = json.loads(bytes(rbuf[:nl]))
                del rbuf[:nl + 1]
                ev, rid = frame.get("event"), frame.get("id")
                if ev == "done":
                    responses[rid] = frame
                elif ev == "reject":
                    rejects += 1
                    retries += 1
                    # re-arm with the server's hint + exponential backoff
                    att = attempts[rid] = attempts.get(rid, 0) + 1
                    if rid not in payloads or att > 10:
                        raise RuntimeError(
                            f"request {rid!r} cannot be retried "
                            f"(attempt {att})")
                    delay = max(float(frame.get("retry_after_s", 0.0)),
                                retry_backoff_s * (2 ** att))
                    sends.append((time_fn() - t0 + delay, payloads[rid],
                                  rid, att))
                elif ev == "error":
                    raise RuntimeError(
                        f"server refused {rid!r}: {frame.get('error')}")
    finally:
        sel.close()
        sock.close()
    return {"responses": responses, "rejects": int(rejects),
            "retries": int(retries), "wall_s": float(time_fn() - t0)}
