from distributed_lion_tpu.ops.codec import (
    pack_signs,
    unpack_signs,
    packed_size,
    wire_bytes_per_param,
)
