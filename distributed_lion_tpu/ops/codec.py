"""1-bit sign codec: pack boolean sign votes into a true uint8 wire format.

Capability parity with the reference's codec helpers
(/root/reference/distributed_lion.py:14-31 ``flatten_and_pad`` /
``restore_flattened_tensor`` and :75-77 / :84-88 inline bit pack/unpack), with
two deliberate differences:

1. **Real uint8 on the wire.** The reference's ``(bool.byte() << arange(8)).sum(-1)``
   silently promotes to int64, shipping 8 bytes per 8 params (SURVEY §2.3, wire
   format bug). Here the packed dtype is uint8 — 1 bit/param as the algorithm
   intends — an 8x wire-volume reduction.
2. **Static shapes.** JAX/XLA requires compile-time shapes, so padding is
   computed from the static leaf size; everything jit-compiles to vector ops.

All functions are pure and shape-polymorphic at trace time (no data-dependent
control flow), so they fuse into the surrounding optimizer update under jit.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def packed_size(n: int) -> int:
    """Number of uint8 bytes needed to pack ``n`` sign bits (ceil(n/8))."""
    return (n + 7) // 8


def parse_wire(wire: str) -> tuple[str, int | None]:
    """Parse a wire-format string into ``(kind, group_size)``.

    Plain formats — ``sign_psum`` / ``packed_allgather`` / ``packed_a2a`` —
    parse to ``(wire, None)``. The hierarchical format ``"hier:<g>"`` parses
    to ``("hier", g)``: g consecutive workers form an ICI subgroup that
    reduce-scatters ±1 ballots on-fabric (each member owns 1/g of the
    coordinates), and only the owners' bit-packed 1-bit verdict chunks cross
    the (DCN) boundary between groups. Raises ValueError on anything else —
    single source of truth for wire validation (optimizer, trainer, byte
    accounting)."""
    if wire.startswith("hier:"):
        try:
            g = int(wire.split(":", 1)[1])
        except ValueError:
            raise ValueError(f"bad hier wire spec {wire!r}: expected 'hier:<int>'")
        if g < 1:
            raise ValueError(f"hier group size must be >= 1, got {g}")
        return "hier", g
    if wire in ("sign_psum", "packed_allgather", "packed_a2a"):
        return wire, None
    raise ValueError(f"unknown wire format: {wire!r}")


def vote_chunk_elems(n: int, vote_every: int) -> int:
    """Coordinates refreshed per step under ``vote_every`` lazy refresh
    (optim.distributed_lion): the ballot vector is padded so every one of the
    K slots is an equal, byte-aligned chunk. Single source of truth for the
    optimizer's slicing and the byte accounting below."""
    return max(8, -(-n // (8 * vote_every)) * 8)


def a2a_chunk_bytes(n: int, world_size: int) -> int:
    """uint8 bytes per worker-chunk in the packed_a2a wire: the ballot vector
    is padded so every worker owns an equal ceil(n/8W)-byte chunk. Single
    source of truth for collectives._packed_a2a_elect and the byte
    accounting below."""
    return max(1, -(-n // (8 * world_size)))


def pack_signs(positive: jnp.ndarray) -> jnp.ndarray:
    """Pack a boolean array (True = +1 vote) into uint8, 8 votes per byte.

    Mirrors the reference's flatten→pad-to-multiple-of-8→bit-shift-pack
    (/root/reference/distributed_lion.py:71-77) but with an actual uint8
    result. Padding bits are zeros; they are dropped again by
    :func:`unpack_signs`, so they never bias a vote (the reference trims
    padding before voting too, distributed_lion.py:88).

    Args:
        positive: bool array of any shape.

    Returns:
        uint8 array of shape ``(packed_size(positive.size),)``.
    """
    flat = positive.reshape(-1).astype(jnp.uint8)
    n = flat.shape[0]
    pad = (-n) % 8
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.uint8)])
    lanes = flat.reshape(-1, 8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    return jnp.sum(lanes << shifts, axis=-1).astype(jnp.uint8)


def unpack_signs(packed: jnp.ndarray, shape: tuple[int, ...]) -> jnp.ndarray:
    """Inverse of :func:`pack_signs`: uint8 bytes → bool array of ``shape``.

    Mirrors the reference's ``(x >> arange(8)) % 2 == 1`` unpack + trim +
    reshape (/root/reference/distributed_lion.py:84-88, 27-31).
    """
    n = int(np.prod(shape)) if shape else 1
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (packed[:, None] >> shifts) & 1
    return bits.reshape(-1)[:n].reshape(shape).astype(jnp.bool_)


def wire_bytes_per_param(num_params: int, world_size: int, wire: str,
                         vote_every: int = 1, accum_steps: int = 1) -> dict:
    """Accounting for bytes RECEIVED per worker, per optimizer step.

    The reference ships int64-packed tensors via all_gather: every worker
    receives ``world * ceil(n/8) * 8`` bytes per step
    (/root/reference/distributed_lion.py:80-81; dtype verified in SURVEY §2.3).
    BASELINE.md's comm budget asks for ≤ 1/32 of a bf16 gradient all-reduce
    (2 bytes/param → ≤ 0.5 bit/param).

    Two honest ways to judge that budget, both reported:

    - ``bits_per_param`` / ``vs_bf16_allreduce``: per *optimizer step*,
      against ONE bf16 all-reduce. ``packed_a2a`` is ~2 bits/param here
      (4x over budget); combining it with ``vote_every >= 4`` lazy refresh
      divides the wire by K and meets the budget outright.
    - ``bits_per_param_per_microbatch`` / ``vs_bf16_allreduce_equal_tokens``:
      amortized over ``accum_steps`` gradient-accumulation microbatches,
      against the bf16 volume DDP moves for the SAME tokens when it syncs
      every backward (torch DDP's default without ``no_sync``). Under the
      reference's canonical config (accum 8, README.md:31) ``packed_a2a``
      is 0.25 bit/param/microbatch — under budget with no algorithm change.

    Args:
        num_params: total parameters voted on.
        world_size: number of data-parallel voters.
        wire: 'sign_psum' (int8 on-fabric all-reduce), 'packed_allgather'
            (1-bit uint8 all-gather), 'packed_a2a' (two-phase 1-bit
            all_to_all + all_gather; ~2 bits/param, W-independent), or
            'hier:<g>' (two-level chunked vote: ballot reduce-scatter inside
            g-worker ICI subgroups, cross-group ring of the owners' packed
            1-bit verdict chunks, intra-group all-gather of the elected
            bits — the ``dcn_bytes_per_step`` extra key reports the
            cross-group leg alone, (W/g − 1)/g bits/param, the volume that
            actually rides the slow fabric on a multi-host mesh).
        vote_every: lazy-refresh period K (optim.distributed_lion): each step
            votes only ceil(n/K) coordinates → wire volume ÷ K.
        accum_steps: gradient-accumulation microbatches per optimizer step
            (for the equal-tokens comparison only).

    Returns:
        dict with bytes received per worker per optimizer step for this
        build, the reference, and a bf16 gradient all-reduce, plus both
        bits/param views.
    """
    kind, group = parse_wire(wire)
    n_voted = (num_params if vote_every <= 1
               else min(num_params, vote_chunk_elems(num_params, vote_every)))
    extras: dict = {}
    if kind == "hier":
        if world_size % group:
            raise ValueError(
                f"hier group size {group} does not divide world {world_size}"
            )
        n_groups = world_size // group
        # Mirrors collectives._hier_elect's three chunked ppermute rings:
        #   ICI leg 1 (reduce-scatter of ballots): (g−1) hops × chunk bytes
        #   ICI leg 3 (all-gather of packed elected): (g−1) hops × chunk/8
        #   DCN leg 2 (cross-group packed verdicts): (G−1) hops × chunk/8 —
        #     the flat packed vote's cross-boundary volume divided by g,
        #     because only each member's OWNED 1/g chunk crosses groups.
        acc_bytes = 1 if group <= 127 else 4
        chunk = 8 * a2a_chunk_bytes(n_voted, group)  # same rule as _hier_elect
        dcn = (n_groups - 1) * (chunk // 8)
        ici = (group - 1) * (chunk * acc_bytes + chunk // 8)
        ours = ici + dcn
        extras = {"hier_groups": n_groups, "dcn_bytes_per_step": dcn,
                  "dcn_bits_per_param": 8.0 * dcn / max(num_params, 1)}
    elif wire == "sign_psum":
        # Ring all-reduce of the ballot tensor: received payload per worker ≈
        # N bytes at the accumulator width (reduction happens on-fabric,
        # receive volume independent of W). int8 is exact only while partial
        # sums fit (W ≤ 127); larger worlds promote to int32, matching
        # collectives.majority_vote_psum.
        acc_bytes = 1 if world_size <= 127 else 4
        ours = n_voted * acc_bytes
    elif wire == "packed_allgather":
        ours = world_size * packed_size(n_voted)
    elif wire == "packed_a2a":
        # phase 1: (W-1) peers each send me their packed copy of my chunk;
        # phase 2: (W-1) peers each send me their chunk's packed verdict.
        ours = 2 * (world_size - 1) * a2a_chunk_bytes(n_voted, world_size)
    else:
        raise ValueError(f"unknown wire format: {wire!r}")
    if world_size <= 1:
        # one voter: every wire short-circuits (a psum/all_gather over a
        # 1-device axis is a no-op — no bytes cross any fabric). Reporting
        # the nominal ballot size here made single-chip metrics claim
        # MB/step of phantom traffic (observed in run_clm W=1 logs).
        ours = 0
    reference = world_size * packed_size(num_params) * 8  # int64 lanes
    bf16_allreduce = 2 * num_params
    if world_size <= 1:
        # the comparison baselines short-circuit identically at W=1 (a DDP
        # all-reduce over one device moves nothing either) — zero them so
        # the ratios read 0/0-style N/A, not an advantage over phantom
        # baseline traffic
        reference = bf16_allreduce = 0
    bits = 8.0 * ours / max(num_params, 1)
    return extras | {
        "wire": wire,
        "vote_every": vote_every,
        "bytes_per_step": ours,
        "bits_per_param": bits,
        "bits_per_param_per_microbatch": bits / max(accum_steps, 1),
        "reference_bytes_per_step": reference,
        "bf16_allreduce_bytes_per_step": bf16_allreduce,
        "vs_bf16_allreduce": ours / max(bf16_allreduce, 1),
        "vs_bf16_allreduce_equal_tokens":
            ours / max(bf16_allreduce * max(accum_steps, 1), 1),
    }
