"""1-bit sign codec: pack boolean sign votes into a true uint8 wire format.

Capability parity with the reference's codec helpers
(/root/reference/distributed_lion.py:14-31 ``flatten_and_pad`` /
``restore_flattened_tensor`` and :75-77 / :84-88 inline bit pack/unpack), with
two deliberate differences:

1. **Real uint8 on the wire.** The reference's ``(bool.byte() << arange(8)).sum(-1)``
   silently promotes to int64, shipping 8 bytes per 8 params (SURVEY §2.3, wire
   format bug). Here the packed dtype is uint8 — 1 bit/param as the algorithm
   intends — an 8x wire-volume reduction.
2. **Static shapes.** JAX/XLA requires compile-time shapes, so padding is
   computed from the static leaf size; everything jit-compiles to vector ops.

All functions are pure and shape-polymorphic at trace time (no data-dependent
control flow), so they fuse into the surrounding optimizer update under jit.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def packed_size(n: int) -> int:
    """Number of uint8 bytes needed to pack ``n`` sign bits (ceil(n/8))."""
    return (n + 7) // 8


def parse_wire(wire: str) -> tuple[str, int | None]:
    """Parse a wire-format string into ``(kind, group_size)``.

    Plain formats — ``sign_psum`` / ``packed_allgather`` / ``packed_a2a`` —
    parse to ``(wire, None)``. The hierarchical format ``"hier:<g>"`` parses
    to ``("hier", g)``: g consecutive workers form an ICI subgroup that
    reduce-scatters ±1 ballots on-fabric (each member owns 1/g of the
    coordinates), and only the owners' bit-packed 1-bit verdict chunks cross
    the (DCN) boundary between groups. Raises ValueError on anything else —
    single source of truth for wire validation (optimizer, trainer, byte
    accounting)."""
    if wire.startswith("hier:"):
        try:
            g = int(wire.split(":", 1)[1])
        except ValueError:
            raise ValueError(f"bad hier wire spec {wire!r}: expected 'hier:<int>'")
        if g < 1:
            raise ValueError(f"hier group size must be >= 1, got {g}")
        return "hier", g
    if wire in ("sign_psum", "packed_allgather", "packed_a2a"):
        return wire, None
    raise ValueError(f"unknown wire format: {wire!r}")


def vote_chunk_elems(n: int, vote_every: int) -> int:
    """Coordinates refreshed per step under ``vote_every`` lazy refresh
    (optim.distributed_lion): the ballot vector is padded so every one of the
    K slots is an equal, byte-aligned chunk. Single source of truth for the
    optimizer's slicing and the byte accounting below."""
    return max(8, -(-n // (8 * vote_every)) * 8)


def bucket_alignment(world_size: int, wire: str) -> int:
    """Element alignment of bucket boundaries for ``wire`` (all but the last
    bucket are multiples of this). Chosen so that splitting a ballot at these
    boundaries changes NOTHING about what each wire moves: every full bucket
    packs to whole bytes (8), owns whole per-worker a2a chunks (8·W), or whole
    per-member hier chunks (8·g). That alignment is exactly what makes the
    per-bucket byte accounting sum to the unbucketed totals (ceil() terms
    become exact for every bucket but the last, and the last bucket's ceil
    absorbs precisely the global remainder)."""
    kind, group = parse_wire(wire)
    if kind == "packed_a2a":
        return 8 * world_size
    if kind == "hier":
        return 8 * group
    return 8  # sign_psum / packed_allgather: byte-pack granularity


def bucket_bounds(n: int, vote_buckets: int, world_size: int,
                  wire: str) -> list[tuple[int, int]]:
    """Split an ``n``-coordinate ballot into ≤ ``vote_buckets`` contiguous
    ``(start, size)`` chunks, boundaries aligned per :func:`bucket_alignment`.

    Single source of truth for the bucketed vote collectives
    (parallel.collectives), the optimizer's software-pipelined bucket loop
    (optim.distributed_lion), and the bucketed byte accounting below — the
    three MUST slice identically or accounting drifts from what moves.

    Invariants: chunks tile [0, n) exactly in order; every chunk but the
    last is a multiple of the wire alignment; small ballots yield fewer
    (possibly 1) buckets rather than empty ones.
    """
    if vote_buckets < 1:
        raise ValueError(f"vote_buckets must be >= 1, got {vote_buckets}")
    if n <= 0:
        return []
    align = bucket_alignment(world_size, wire)
    per = -(-n // vote_buckets)            # ceil: target bucket size
    per = -(-per // align) * align         # rounded up to the wire alignment
    bounds = []
    off = 0
    while off < n:
        size = min(per, n - off)
        bounds.append((off, size))
        off += size
    return bounds


def a2a_chunk_bytes(n: int, world_size: int) -> int:
    """uint8 bytes per worker-chunk in the packed_a2a wire: the ballot vector
    is padded so every worker owns an equal ceil(n/8W)-byte chunk. Single
    source of truth for collectives._packed_a2a_elect and the byte
    accounting below."""
    return max(1, -(-n // (8 * world_size)))


def hier_chunk_slot_bytes(nb: int, world_size: int, group: int) -> int:
    """uint8 bytes of one BUCKET's in-flight DCN slot segment for an
    ``nb``-coordinate ballot chunk on the ``hier:<g>`` wire: a [n_groups]
    launch-time group-alive byte mask followed by the [n_groups, chunk/8]
    packed per-group level-2 verdict stack for this worker's owned chunk
    (collectives.hier_launch's exact output)."""
    n_groups = world_size // group
    return n_groups * (1 + a2a_chunk_bytes(nb, group))


def hier_ring_slot_bytes(n: int, world_size: int, group: int,
                         vote_buckets: int = 1, vote_every: int = 1) -> int:
    """uint8 bytes of ONE in-flight slot of the hier wire's cross-step DCN
    ring (``--dcn_pipeline_depth``): the concatenation of the per-bucket
    segments (:func:`hier_chunk_slot_bytes`) over ``bucket_bounds`` of the
    per-step ballot.

    Single source of truth for the optimizer's ``dcn_ring`` state layout
    (optim.distributed_lion), the collectives' launch/consume slicing
    (collectives.hier_launch / hier_consume) and the trainer's restore
    templates — the three MUST agree or a checkpointed in-flight tally
    lands on the wrong coordinates.
    """
    if world_size % group:
        raise ValueError(
            f"hier wire: group size {group} does not divide world "
            f"{world_size}")
    # under lazy refresh the wire is handed the PADDED rotating slice
    # (optim._elect_lazy slices exactly vote_chunk_elems coordinates), so
    # the ring is laid out for the slice length, not min(n, slice)
    ballot = n if vote_every <= 1 else vote_chunk_elems(n, vote_every)
    return sum(hier_chunk_slot_bytes(size, world_size, group)
               for _, size in bucket_bounds(ballot, max(vote_buckets, 1),
                                            world_size, f"hier:{group}"))


def pack_signs(positive: jnp.ndarray) -> jnp.ndarray:
    """Pack a boolean array (True = +1 vote) into uint8, 8 votes per byte.

    Mirrors the reference's flatten→pad-to-multiple-of-8→bit-shift-pack
    (/root/reference/distributed_lion.py:71-77) but with an actual uint8
    result. Padding bits are zeros; they are dropped again by
    :func:`unpack_signs`, so they never bias a vote (the reference trims
    padding before voting too, distributed_lion.py:88).

    Args:
        positive: bool array of any shape.

    Returns:
        uint8 array of shape ``(packed_size(positive.size),)``.
    """
    flat = positive.reshape(-1).astype(jnp.uint8)
    n = flat.shape[0]
    pad = (-n) % 8
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.uint8)])
    lanes = flat.reshape(-1, 8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    return jnp.sum(lanes << shifts, axis=-1).astype(jnp.uint8)


def unpack_signs(packed: jnp.ndarray, shape: tuple[int, ...]) -> jnp.ndarray:
    """Inverse of :func:`pack_signs`: uint8 bytes → bool array of ``shape``.

    Mirrors the reference's ``(x >> arange(8)) % 2 == 1`` unpack + trim +
    reshape (/root/reference/distributed_lion.py:84-88, 27-31).
    """
    n = int(np.prod(shape)) if shape else 1
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (packed[:, None] >> shifts) & 1
    return bits.reshape(-1)[:n].reshape(shape).astype(jnp.bool_)


def _recv_bytes(n: int, world_size: int, kind: str,
                group: int | None) -> tuple[int, int]:
    """Bytes RECEIVED per worker for ONE contiguous ``n``-coordinate ballot
    on this wire: ``(total_bytes, dcn_leg_bytes)``. The per-bucket unit the
    (possibly bucketed) accounting below sums over."""
    if kind == "hier":
        n_groups = world_size // group
        # Mirrors collectives._hier_elect's three chunked ppermute rings:
        #   ICI leg 1 (reduce-scatter of ballots): (g−1) hops × chunk bytes
        #   ICI leg 3 (all-gather of packed elected): (g−1) hops × chunk/8
        #   DCN leg 2 (cross-group packed verdicts): (G−1) hops × chunk/8 —
        #     the flat packed vote's cross-boundary volume divided by g,
        #     because only each member's OWNED 1/g chunk crosses groups.
        acc_bytes = 1 if group <= 127 else 4
        chunk = 8 * a2a_chunk_bytes(n, group)  # same rule as _hier_elect
        dcn = (n_groups - 1) * (chunk // 8)
        ici = (group - 1) * (chunk * acc_bytes + chunk // 8)
        return ici + dcn, dcn
    if kind == "sign_psum":
        # Ring all-reduce of the ballot tensor: received payload per worker ≈
        # N bytes at the accumulator width (reduction happens on-fabric,
        # receive volume independent of W). int8 is exact only while partial
        # sums fit (W ≤ 127); larger worlds promote to int32, matching
        # collectives.majority_vote_psum.
        acc_bytes = 1 if world_size <= 127 else 4
        return n * acc_bytes, 0
    if kind == "packed_allgather":
        return world_size * packed_size(n), 0
    if kind == "packed_a2a":
        # phase 1: (W-1) peers each send me their packed copy of my chunk;
        # phase 2: (W-1) peers each send me their chunk's packed verdict.
        return 2 * (world_size - 1) * a2a_chunk_bytes(n, world_size), 0
    raise ValueError(f"unknown wire format: {kind!r}")


def wire_bytes_per_param(num_params: int, world_size: int, wire: str,
                         vote_every: int = 1, accum_steps: int = 1,
                         vote_buckets: int = 1,
                         dcn_pipeline_depth: int = 0) -> dict:
    """Accounting for bytes RECEIVED per worker, per optimizer step.

    The reference ships int64-packed tensors via all_gather: every worker
    receives ``world * ceil(n/8) * 8`` bytes per step
    (/root/reference/distributed_lion.py:80-81; dtype verified in SURVEY §2.3).
    BASELINE.md's comm budget asks for ≤ 1/32 of a bf16 gradient all-reduce
    (2 bytes/param → ≤ 0.5 bit/param).

    Two honest ways to judge that budget, both reported:

    - ``bits_per_param`` / ``vs_bf16_allreduce``: per *optimizer step*,
      against ONE bf16 all-reduce. ``packed_a2a`` is ~2 bits/param here
      (4x over budget); combining it with ``vote_every >= 4`` lazy refresh
      divides the wire by K and meets the budget outright.
    - ``bits_per_param_per_microbatch`` / ``vs_bf16_allreduce_equal_tokens``:
      amortized over ``accum_steps`` gradient-accumulation microbatches,
      against the bf16 volume DDP moves for the SAME tokens when it syncs
      every backward (torch DDP's default without ``no_sync``). Under the
      reference's canonical config (accum 8, README.md:31) ``packed_a2a``
      is 0.25 bit/param/microbatch — under budget with no algorithm change.

    Args:
        num_params: total parameters voted on.
        world_size: number of data-parallel voters.
        wire: 'sign_psum' (int8 on-fabric all-reduce), 'packed_allgather'
            (1-bit uint8 all-gather), 'packed_a2a' (two-phase 1-bit
            all_to_all + all_gather; ~2 bits/param, W-independent), or
            'hier:<g>' (two-level chunked vote: ballot reduce-scatter inside
            g-worker ICI subgroups, cross-group ring of the owners' packed
            1-bit verdict chunks, intra-group all-gather of the elected
            bits — the ``dcn_bytes_per_step`` extra key reports the
            cross-group leg alone, (W/g − 1)/g bits/param, the volume that
            actually rides the slow fabric on a multi-host mesh).
        vote_every: lazy-refresh period K (optim.distributed_lion): each step
            votes only ceil(n/K) coordinates → wire volume ÷ K.
        accum_steps: gradient-accumulation microbatches per optimizer step
            (for the equal-tokens comparison only).
        vote_buckets: number of contiguous ballot chunks voted as separate
            (pipelined) collectives (optim.distributed_lion bucket loop).
            Accounted as the SUM of the per-bucket wires over
            :func:`bucket_bounds` — which, by the bucket-boundary alignment,
            is exactly the unbucketed total: bucketing changes when bytes
            move (overlapped with compute), never how many.
        dcn_pipeline_depth: cross-step pipeline depth of the hier wire's
            level-2 (DCN) leg (optim.distributed_lion): at depth d > 0 the
            cross-group packed-verdict ring launched at step t is consumed
            only at step t+d, so its round-trip latency hides behind d
            steps of compute. The OVERLAPPED leg still moves exactly the
            same bytes every step — one launch and one consume execute per
            step in steady state, so ``bytes_per_step``/``dcn_bytes_per_
            step`` (and the measured counters they're cross-checked
            against: ``comm_drift_bytes`` stays 0) are depth-invariant.
            What depth changes is the ``dcn_overlap_frac`` extra: the
            fraction of the DCN leg's LATENCY eligible to leave the
            critical path (1.0 once the leg rides the ring, 0.0 for the
            synchronous depth-0 wire). The measured counterpart comes from
            the bench_dcn ablation (scripts/bench_dcn.py).

    Returns:
        dict with bytes received per worker per optimizer step for this
        build, the reference, and a bf16 gradient all-reduce, plus both
        bits/param views.
    """
    kind, group = parse_wire(wire)
    n_voted = (num_params if vote_every <= 1
               else min(num_params, vote_chunk_elems(num_params, vote_every)))
    extras: dict = {}
    if kind == "hier" and world_size % group:
        raise ValueError(
            f"hier group size {group} does not divide world {world_size}"
        )
    # One collective per bucket, each accounted with the same per-ballot
    # formula (_recv_bytes). bucket_bounds' alignment guarantees the sum is
    # EXACTLY the vote_buckets=1 number — pinned by the conservation test in
    # tests/test_vote_buckets.py.
    per_bucket = [_recv_bytes(size, world_size, kind, group)
                  for _, size in bucket_bounds(n_voted, max(vote_buckets, 1),
                                               world_size, wire)]
    ours = sum(b for b, _ in per_bucket)
    # Analytic pipelineable fraction of the wire: the optimizer's software
    # pipeline (optim.distributed_lion._step_pallas) overlaps bucket k's
    # collective with bucket k−1's fused apply, so every bucket AFTER the
    # first can hide behind compute — the fraction of wire bytes eligible
    # for overlap is buckets[1:]'s share. 0.0 for the monolithic vote and
    # at world=1 (no wire to hide). The MEASURED counterpart lives in
    # bench.py's overlap-ablation rows (comm_overlap_frac).
    overlappable = (sum(b for b, _ in per_bucket[1:]) / ours
                    if ours and world_size > 1 else 0.0)
    if kind == "hier":
        dcn = sum(d for _, d in per_bucket)
        # the level-2 leg's latency leaves the critical path entirely once
        # it rides the cross-step ring (depth ≥ 1) — and only then; no leg
        # exists to hide at W=1 or single-group (g=W) topologies
        dcn_overlap = (1.0 if (dcn_pipeline_depth > 0 and dcn > 0
                               and world_size > 1) else 0.0)
        extras = {"hier_groups": world_size // group,
                  "dcn_bytes_per_step": dcn,
                  "dcn_bits_per_param": 8.0 * dcn / max(num_params, 1),
                  "dcn_pipeline_depth": max(dcn_pipeline_depth, 0),
                  "dcn_overlap_frac": dcn_overlap}
    if world_size <= 1:
        # one voter: every wire short-circuits (a psum/all_gather over a
        # 1-device axis is a no-op — no bytes cross any fabric). Reporting
        # the nominal ballot size here made single-chip metrics claim
        # MB/step of phantom traffic (observed in run_clm W=1 logs).
        ours = 0
    reference = world_size * packed_size(num_params) * 8  # int64 lanes
    bf16_allreduce = 2 * num_params
    if world_size <= 1:
        # the comparison baselines short-circuit identically at W=1 (a DDP
        # all-reduce over one device moves nothing either) — zero them so
        # the ratios read 0/0-style N/A, not an advantage over phantom
        # baseline traffic
        reference = bf16_allreduce = 0
    bits = 8.0 * ours / max(num_params, 1)
    return extras | {
        "wire": wire,
        "vote_every": vote_every,
        "vote_buckets": max(vote_buckets, 1),
        "overlappable_wire_frac": overlappable,
        "bytes_per_step": ours,
        "bits_per_param": bits,
        "bits_per_param_per_microbatch": bits / max(accum_steps, 1),
        "reference_bytes_per_step": reference,
        "bf16_allreduce_bytes_per_step": bf16_allreduce,
        "vs_bf16_allreduce": ours / max(bf16_allreduce, 1),
        "vs_bf16_allreduce_equal_tokens":
            ours / max(bf16_allreduce * max(accum_steps, 1), 1),
    }
