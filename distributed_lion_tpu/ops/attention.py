"""Attention implementations with a single dispatch point.

- ``xla``   — materialized-scores reference: einsum → masked f32 softmax →
  einsum. XLA's fusion is already MXU-optimal at moderate T — measured ~1.4x
  FASTER than the flash kernel at T=1024 on a real v5e chip (82.3k vs 59.2k
  tokens/s/chip on the GPT-2 124M train step; scripts/SWEEP_v5e.md records
  the sweep) — so it is the default below the ``auto`` threshold.
- ``flash`` — Pallas TPU flash attention (jax's bundled
  ``pallas.ops.tpu.flash_attention``): O(T) memory online-softmax blocking,
  the choice for long sequences where [B,H,T,T] scores would blow HBM.
- ``auto``  — flash on TPU for T ≥ 2048, else xla.

All take q, k, v as [B, H, T, head_dim] and return [B, H, T, head_dim] in
q's dtype. Causal only (decoder framework).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def attention_xla(q, k, v, *, causal: bool = True):
    T = q.shape[2]
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v, preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def attention_flash(q, k, v, *, causal: bool = True):
    from jax.experimental.pallas.ops.tpu.flash_attention import flash_attention

    return flash_attention(
        q, k, v, causal=causal, sm_scale=1.0 / math.sqrt(q.shape[-1])
    ).astype(q.dtype)


def attention(q, k, v, *, causal: bool = True, impl: str = "auto"):
    if impl == "auto":
        impl = "flash" if (jax.default_backend() == "tpu" and q.shape[2] >= 2048) else "xla"
    if impl == "flash":
        return attention_flash(q, k, v, causal=causal)
    if impl == "xla":
        return attention_xla(q, k, v, causal=causal)
    raise ValueError(f"unknown attention impl {impl!r}")
