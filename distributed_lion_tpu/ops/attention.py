"""Attention implementations with a single dispatch point.

- ``xla``   — materialized-scores reference: einsum → masked f32 softmax →
  einsum. Beats the flash kernel's DEFAULT tiles at T=1024 on v5e (82.3k vs
  59.2k tokens/s/chip, GPT-2 124M train step) — but tile-TUNED flash
  (``flash_block_q=512, flash_block_kv=1024``) beats xla by ~12% at the
  same shape (92.2k; scripts/SWEEP_v5e.md round-3 sweep). ``xla`` stays the
  ``auto`` default below the threshold because the tuned tiles are a
  per-shape measurement, not a safe generalization.
- ``xla_bf16`` — ``xla`` with the [B,H,T,T] scores stored in bf16 (softmax
  still f32 internally): halves the largest attention intermediate's HBM
  round-trip at ~1e-2 relative error on probs. Opt-in throughput config.
- ``flash`` — Pallas TPU flash attention (jax's bundled
  ``pallas.ops.tpu.flash_attention``): O(T) memory online-softmax blocking,
  the choice for long sequences where [B,H,T,T] scores would blow HBM.
- ``splash`` — the newer Pallas TPU splash kernel family (sparse-mask
  blocking); faster than ``flash`` at moderate T but still behind ``xla``
  at T=1024 on v5e (scripts/SWEEP_v5e.md).
- ``auto``  — on TPU, in priority order: caller-pinned tiles → flash with
  those tiles at any shape (an explicit ``auto@BQxBKV`` spec is an
  operator decision — it must stay sweepable even when a cache entry
  exists for the shape); otherwise an autotune-cache hit for this
  device_kind × (T, head_dim) × dtype → flash with the MEASURED winning
  tiles (ops/autotune, knob ``flash_tiles`` — produced by
  ``cli/run_tune``); flash for T ≥ 2048 (its memory regime); tile-tuned
  flash (512x1024) at the swept flagship shape (T=1024, head_dim=64 —
  GPT-2); xla everywhere else (tuned tiles are per-shape measurements,
  not safe generalizations). Off TPU: always xla (pinned forward tiles
  are unused there — Pallas kernels are TPU-only).

All take q, k, v as [B, H, T, head_dim] and return [B, H, T, head_dim] in
q's dtype. Causal only (decoder framework).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def attention_xla(q, k, v, *, causal: bool = True,
                  score_dtype=jnp.float32):
    """Materialized-scores attention. ``score_dtype=jnp.bfloat16`` is the
    ``xla_bf16`` impl: the [B, H, T, T] scores tensor — the largest
    attention intermediate (201 MB/layer at mb4 T=1024 in f32) and pure HBM
    traffic between the two matmuls — is stored in bf16, halving its
    round-trip. The softmax always runs in f32 (the upcast fuses into the
    softmax elementwise chain, costing registers, not HBM), so only the one
    rounding of the scores differs; max-subtraction bounds the exponent so
    bf16's 8 mantissa bits cost ~1e-2 relative on probs — an opt-in
    throughput config, not the parity default."""
    T = q.shape[2]
    scale = 1.0 / math.sqrt(q.shape[-1])
    # accumulate in f32 regardless of score_dtype; only the STORED scores
    # are rounded (the cast fuses into the matmul/mask epilogue, so the
    # HBM write is score_dtype-wide) — rounding is the only delta vs f32
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = (scores * scale).astype(score_dtype)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(mask, scores, jnp.asarray(-1e30, score_dtype))
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v, preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def attention_flash(q, k, v, *, causal: bool = True,
                    block_q: int = 0, block_kv: int = 0,
                    block_q_bwd: int = 0, block_kv_bwd: int = 0):
    """Pallas TPU flash attention. ``block_q``/``block_kv`` override the
    kernel's VMEM tile sizes (0 = library defaults); exposed because the
    default blocking lost to XLA at T=1024 on v5e (scripts/SWEEP_v5e.md) and
    tile shape is the first knob to turn. ``block_q_bwd``/``block_kv_bwd``
    tune the dq/dkv backward passes independently (0 = inherit fwd) — the
    backward is ~2× the fwd FLOPs with different operand shapes, so its
    optimum tile need not match the forward's."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention,
    )

    T = q.shape[2]
    bs = None
    if block_q or block_kv or block_q_bwd or block_kv_bwd:
        bq = min(block_q or 512, T)
        bkv = min(block_kv or 512, T)
        bqb = min(block_q_bwd or bq, T)
        bkvb = min(block_kv_bwd or bkv, T)
        bs = BlockSizes(
            block_q=bq, block_k_major=bkv, block_k=bkv, block_b=1,
            block_q_major_dkv=bqb, block_k_major_dkv=bkvb, block_k_dkv=bkvb,
            block_q_dkv=bqb, block_k_major_dq=bkvb, block_k_dq=bkvb,
            block_q_dq=bqb,
        )
    return flash_attention(
        q, k, v, causal=causal, sm_scale=1.0 / math.sqrt(q.shape[-1]),
        block_sizes=bs,
    ).astype(q.dtype)


def attention_splash(q, k, v, *, causal: bool = True,
                     block_q: int = 0, block_kv: int = 0,
                     interpret: bool = False):
    """Splash attention (the newer Pallas TPU kernel family): sparse-mask
    blocking, fused bwd option — typically faster than the older flash
    kernel at moderate T. Takes the same [B, H, T, hd] as the others; the
    kernel is per-(heads, T, hd) so batch rides a vmap. q is pre-scaled
    (splash applies no sm_scale)."""
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as ml,
    )

    B, H, T, hd = q.shape
    # the installed splash kernel requires head_dim % 128 == 0 (lane width);
    # GPT-2's hd=64 (and any other non-multiple) is padded up with zero
    # columns and the output sliced back. Exact, not approximate: q·k over
    # the zero columns adds nothing to any score, and the zero v columns
    # only produce output columns that are sliced away. The pad costs real
    # MXU FLOPs (hd 64 → 128 doubles the qk/pv inner dim), which is why
    # `auto` never dispatches here — explicit splash requests and the
    # autotune tuner (which times the kernel PADDED, so its numbers stay
    # honest) accept the cost knowingly.
    hd_pad = -(-hd // 128) * 128
    if hd_pad != hd:
        pad = [(0, 0)] * 3 + [(0, hd_pad - hd)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))
    one = ml.CausalMask((T, T)) if causal else ml.FullMask((T, T))
    mask = ml.MultiHeadMask([one for _ in range(H)])
    bs = None
    if block_q or block_kv:
        bq = min(block_q or 512, T)
        bkv = min(block_kv or 512, T)
        bs = sk.BlockSizes(block_q=bq, block_kv=bkv,
                           block_q_dkv=bq, block_kv_dkv=bkv,
                           block_q_dq=bq, block_kv_dq=bkv)
    kernel = sk.make_splash_mha_single_device(mask=mask, block_sizes=bs,
                                              interpret=interpret)
    # scale by the REAL head_dim — the zero pad must not change the softmax
    qs = (q * (1.0 / math.sqrt(hd))).astype(q.dtype)
    out = jax.vmap(kernel)(qs, k, v)
    if hd_pad != hd:
        out = out[..., :hd]
    return out.astype(q.dtype)


# ------------------------------------------------------------- paged decode
# The serving engine's KV layout (serve/kv_cache.py, vLLM's PagedAttention
# design): each layer's cache is a fixed pool of pages [num_blocks,
# block_size, kv_heads, head_dim]; a sequence owns an ordered list of page
# indices (its block table). Allocation/free is HOST-side table math — the
# device functions below are pure static-shape gathers/scatters, so the
# decode tick stays one jitted program no matter how sequences come and go.
# The sentinel block index == num_blocks (one past the pool) makes unused
# table entries inert: scatters drop out-of-range writes, gathers fill 0.


def paged_scatter_kv(pages: jnp.ndarray, tables: jnp.ndarray,
                     pos: jnp.ndarray, new: jnp.ndarray,
                     valid=None) -> jnp.ndarray:
    """Write per-row new k (or v) rows into their block-table pages.

    pages  [num_blocks, block_size, KV, hd] — one layer's pool (k or v);
    tables [B, blocks_per_seq] int32 page ids (sentinel = num_blocks);
    pos    [B] int32 — absolute position of each row's FIRST new token;
    new    [B, S, KV, hd] — the S new tokens' projections per row;
    valid  optional [B, S] bool — False entries are dropped (right-padded
    prefill tails must not write garbage pages).

    Token s of row b lands in page ``tables[b, (pos[b]+s)//block_size]`` at
    offset ``(pos[b]+s) % block_size``. Rows whose table entry is the
    sentinel (never allocated — e.g. an inactive decode slot) scatter out
    of range and are dropped by XLA's scatter mode, not branched on.

    A multi-token window commit ([B, S] with S > 1 — the bucketed prefill
    and the speculative verify window, serve/speculate.py) is bit-identical
    to S sequential single-token scatters: the writes land in the same
    (page, offset) cells with the same values, and masked/sentinel writes
    drop identically (pinned by tests/test_serve.py). Per-row VALID COUNTS
    ride ``valid`` as ``arange(S) < counts[:, None]`` — the rejected/padded
    tail never touches a page.
    """
    B, S = new.shape[:2]
    bs = pages.shape[1]
    abs_pos = pos[:, None] + jnp.arange(S, dtype=pos.dtype)[None, :]  # [B,S]
    blk = jnp.take_along_axis(tables, abs_pos // bs, axis=1,
                              mode="clip")  # sentinel rides the VALUE
    if valid is not None:
        # out-of-range page id ⇒ the scatter drops the write
        blk = jnp.where(valid, blk, pages.shape[0])
    off = abs_pos % bs
    flat = new.reshape((B * S,) + new.shape[2:])
    return pages.at[blk.reshape(-1), off.reshape(-1)].set(
        flat, mode="drop", unique_indices=False)


def paged_copy_pages(pages: list, src: jnp.ndarray,
                     dst: jnp.ndarray) -> list:
    """Copy whole pages inside each layer's pool — the device half of
    copy-on-write prefix sharing (serve/kv_cache.BlockTables.cow).

    pages — the engine's per-layer ``[{"k", "v"}]`` pool list;
    src/dst [C] int32 — page-id pairs to copy this dispatch, padded with
    the sentinel (== num_blocks): a sentinel ``dst`` drops the write and a
    sentinel ``src`` gathers zeros (never kept — its dst is sentinel too),
    so one fixed-width jitted program serves any number of copies ≤ C
    without recompiling. The copy is bytewise (no arithmetic): a CoW'd
    page attends bit-identically to the shared original, which is what
    keeps shared-prefix decode pinned to the unshared engine. Under
    tensor parallelism the pool's kv-head axis is sharded and the copy is
    shard-local — page ids are replicated host math."""
    out = []
    for layer in pages:
        out.append({
            name: layer[name].at[dst].set(
                jnp.take(layer[name], src, axis=0, mode="fill",
                         fill_value=0),
                mode="drop", unique_indices=False)
            for name in ("k", "v")
        })
    return out


def paged_gather_kv(pages: jnp.ndarray, tables: jnp.ndarray) -> jnp.ndarray:
    """[num_blocks, bs, KV, hd] pool + [B, nb] tables → [B, nb*bs, KV, hd]
    contiguous per-row history (sentinel pages read as zeros — they are
    masked out of attention by the caller's position bound anyway)."""
    B, nb = tables.shape
    bs = pages.shape[1]
    got = jnp.take(pages, tables, axis=0, mode="fill", fill_value=0)
    return got.reshape((B, nb * bs) + pages.shape[2:])


def paged_decode_attention(q, k_pages, v_pages, tables, pos,
                           start=None):
    """Decode attention over a paged KV cache (new k/v already scattered).

    q [B, H, S, hd] — queries for the S newest tokens of each row (rope
    already applied by the model); k_pages/v_pages [num_blocks, bs, KV, hd];
    tables [B, nb]; pos [B] — absolute position of each row's first new
    token; ``start`` optional [B] — first VALID history slot (left-padded
    batches mask the pad prefix). Returns [B, H, S, hd] in q's dtype.

    The gather reassembles each row's history into the SAME contiguous
    [B, T, KV, hd] layout the dense cache holds, then runs the identical
    masked-softmax einsum chain — so greedy decode through pages is
    bit-identical to the dense path whenever T matches (pinned by
    tests/test_serve.py). GQA kv heads are repeated at attend time, exactly
    like the dense caches store them un-repeated.

    S > 1 is the multi-token window (bucketed prefill; speculative verify,
    serve/speculate.py): query s attends causally INSIDE the window
    (``t_idx <= pos + s``), so a window whose first v entries are valid is
    safe without extra masking — a valid query s < v only ever sees
    history plus window tokens 0..s, all freshly scattered this dispatch;
    queries at invalid positions produce garbage rows the caller discards.
    """
    B, H, S, hd = q.shape
    KV = k_pages.shape[2]
    k_full = paged_gather_kv(k_pages, tables).transpose(0, 2, 1, 3)
    v_full = paged_gather_kv(v_pages, tables).transpose(0, 2, 1, 3)
    if KV != H:
        rep = H // KV
        k_full = jnp.repeat(k_full, rep, axis=1)
        v_full = jnp.repeat(v_full, rep, axis=1)
    T = k_full.shape[2]
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k_full,
                        preferred_element_type=jnp.float32) / math.sqrt(hd)
    t_idx = jnp.arange(T)[None, None, :]
    valid = t_idx <= (pos[:, None] + jnp.arange(S)[None, :])[:, :, None]
    if start is not None:
        valid &= t_idx >= start[:, None, None]
    scores = jnp.where(valid[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, v_full,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def parse_attn_spec(spec: str) -> tuple[str, int, int, int, int]:
    """Parse an attention spec ``impl[@BQxBKV[@BQBxBKVB]]`` into
    ``(impl, block_q, block_kv, block_q_bwd, block_kv_bwd)`` — e.g.
    ``"flash@512x1024"`` → ``("flash", 512, 1024, 0, 0)`` and
    ``"flash@512x1024@256x512"`` tunes the BACKWARD tiles independently
    (the bwd passes are ~2× the fwd FLOPs with different operand shapes,
    so their optimum need not match; 0 = inherit the fwd tiles). No ``@``
    → all 0 (kernel defaults). The one grammar shared by bench.py's
    BENCH_ATTN env knob and scripts/bench_sweep.py's config specs."""
    if "@" not in spec:
        return spec, 0, 0, 0, 0
    impl, _, blocks = spec.partition("@")
    fwd, _, bwd = blocks.partition("@")
    bq, bkv = (int(x) for x in fwd.split("x"))
    bqb, bkvb = (int(x) for x in bwd.split("x")) if bwd else (0, 0)
    return impl, bq, bkv, bqb, bkvb


def attention(q, k, v, *, causal: bool = True, impl: str = "auto",
              block_q: int = 0, block_kv: int = 0,
              block_q_bwd: int = 0, block_kv_bwd: int = 0):
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        T = q.shape[2]
        tuned = None
        if on_tpu and not (block_q or block_kv or block_q_bwd or block_kv_bwd):
            # no caller pins → consult the autotune cache (ops/autotune,
            # knob 'flash_tiles'): a measured winner for THIS device_kind
            # × (T, head_dim) × dtype outranks every heuristic below —
            # but never an explicit pin (the elif), which is how sweeps
            # measure non-cached tiles. Device-keyed, so a cache produced
            # elsewhere never leaks here; a corrupt cache is loud and
            # reads as a miss. The lookup is host-side at trace time —
            # one file read per process (module-level memo in autotune).
            from distributed_lion_tpu.ops.autotune import (
                attn_shape_key,
                lookup,
            )

            tuned = lookup("flash_tiles", attn_shape_key(T, q.shape[3]),
                           jnp.dtype(q.dtype).name)
        if tuned:
            impl = "flash"
            block_q = int(tuned.get("block_q", 0))
            block_kv = int(tuned.get("block_kv", 0))
            block_q_bwd = int(tuned.get("block_q_bwd", 0))
            block_kv_bwd = int(tuned.get("block_kv_bwd", 0))
        elif on_tpu and (block_q or block_kv or block_q_bwd or block_kv_bwd):
            # caller-pinned tiles are a flash knob: honor them at ANY shape
            # rather than silently running untiled xla (a config like
            # auto@256x512 would otherwise report numbers and tune nothing
            # — same trap the bwd-tile guard below raises for). Backward-only
            # pins (auto@@BQBxBKVB-style resolved specs) count too: falling
            # through to xla would hit that guard's ValueError instead of
            # honoring the tiles (advisor r4)
            impl = "flash"
        elif on_tpu and T >= 2048:
            impl = "flash"
        elif on_tpu and T == 1024 and q.shape[3] == 64:
            # measured winner at the swept flagship shape — GPT-2 124M,
            # T=1024, head_dim=64: tile-tuned flash beats xla by ~12% on
            # v5e (flash@512x1024 → 98,099 tokens/s/chip vs xla 85.7k,
            # scripts/SWEEP_r3_raw/sweep2.jsonl). The head_dim gate keeps
            # OTHER T=1024 workloads (e.g. Llama-7B, head_dim 128 — the 7B
            # bench leg) on the conservative xla path: the tiles are a
            # per-shape measurement, not a safe generalization
            impl = "flash"
            block_q, block_kv = 512, 1024
        else:
            impl = "xla"
            # auto resolved AWAY from flash (no TPU backend): pinned tiles
            # — bwd like fwd — are flash knobs with nothing left to tune.
            # Drop them instead of tripping the explicit-impl guard below:
            # an auto@...@BQBxBKVB spec must degrade off-TPU exactly like
            # auto@... does, not raise the flash-knob ValueError that
            # exists for EXPLICIT xla/splash requests
            block_q_bwd = block_kv_bwd = 0
    if impl == "flash":
        return attention_flash(q, k, v, causal=causal,
                               block_q=block_q, block_kv=block_kv,
                               block_q_bwd=block_q_bwd,
                               block_kv_bwd=block_kv_bwd)
    if block_q_bwd or block_kv_bwd:
        # fail loudly: a sweep config like splash@128x256@64x128 would
        # otherwise run, report numbers, and silently tune nothing
        raise ValueError(
            f"backward-tile overrides (@BQBxBKVB) are a flash-kernel knob; "
            f"impl {impl!r} does not consume them")
    if impl == "splash":
        return attention_splash(q, k, v, causal=causal,
                                block_q=block_q, block_kv=block_kv)
    if impl == "xla":
        return attention_xla(q, k, v, causal=causal)
    if impl == "xla_bf16":
        return attention_xla(q, k, v, causal=causal,
                             score_dtype=jnp.bfloat16)
    raise ValueError(f"unknown attention impl {impl!r}")
