"""Pure per-leaf Lion math shared by the local and distributed optimizers.

Semantics match the reference update functions:
- local Lion:            /root/reference/distributed_lion.py:47-59
- deterministic 1-bit:   /root/reference/distributed_lion.py:61-96 (sign step)
- stochastic 1-bit:      /root/reference/distributed_lion.py:98-136 (bernoulli
                         binarization with range bound r = (1 + 1/beta1) *
                         max_grad_norm, distributed_lion.py:106-108)

Everything here is elementwise and jit-fusible; no collectives (those live in
``optim.distributed_lion`` / ``parallel.collectives``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def interp(grad: jnp.ndarray, exp_avg: jnp.ndarray, b1: float) -> jnp.ndarray:
    """The raw Lion update direction ``b1*m + (1-b1)*g`` (ref :54, :68, :107)."""
    return exp_avg * b1 + grad * (1.0 - b1)


def momentum_update(grad: jnp.ndarray, exp_avg: jnp.ndarray, b2: float) -> jnp.ndarray:
    """``m ← b2*m + (1-b2)*g`` with the *local* gradient (ref :59, :96, :136).

    Under distributed vote-Lion the momenta deliberately diverge across
    workers — only sign votes are exchanged (SURVEY §2.3 step 7).
    """
    return exp_avg * b2 + grad * (1.0 - b2)


def decay_params(params: jnp.ndarray, lr, wd: float) -> jnp.ndarray:
    """Decoupled weight decay ``p ← p * (1 - lr*wd)`` (ref :50, :64, :101).

    Applied multiplicatively *before* the sign update, matching the
    reference's op ordering so trajectories are comparable bit-for-bit.
    The factor is cast to the param dtype so a float32 LR schedule can
    never silently promote bf16 params.
    """
    factor = jnp.asarray(1.0 - lr * wd, params.dtype)
    return params * factor


def sign_vote_bool(grad: jnp.ndarray, exp_avg: jnp.ndarray, b1: float) -> jnp.ndarray:
    """Deterministic binarization: vote True where the update is > 0.

    The reference computes ``sign(interp) > 0`` (ref :68, :71); zero maps to a
    False (−1) vote, consistent with the tie→−1 rule downstream.
    """
    return interp(grad, exp_avg, b1) > 0


def stochastic_vote_bool(
    key: jax.Array,
    grad: jnp.ndarray,
    exp_avg: jnp.ndarray,
    b1: float,
    max_grad_norm: float,
) -> jnp.ndarray:
    """Stochastic binarization: vote True with prob ``(u + r) / 2r``.

    Unbiased-in-expectation 1-bit quantizer (ref :106-108): with
    ``r = (1 + 1/b1) * max_grad_norm`` and clipped gradients, ``|u| ≤ r`` so
    the probability is in [0, 1]. We clip defensively (the reference would
    raise inside ``torch.bernoulli``; clipping keeps the quantizer total and
    jit-safe — outside the bound it saturates to a deterministic vote).
    """
    r = (1.0 + 1.0 / b1) * max_grad_norm
    u = interp(grad, exp_avg, b1)
    p_up = jnp.clip((u.astype(jnp.float32) + r) / (2.0 * r), 0.0, 1.0)
    return jax.random.bernoulli(key, p_up)


def apply_signed_update(params: jnp.ndarray, vote_pos: jnp.ndarray, lr) -> jnp.ndarray:
    """``p ← p - lr * (vote ? +1 : -1)`` (ref :91-92: ``vote*2 - 1``)."""
    s = jnp.where(vote_pos, 1.0, -1.0).astype(params.dtype)
    return params - jnp.asarray(lr, params.dtype) * s


def local_lion_leaf(params, grad, exp_avg, lr, wd, b1, b2):
    """One full local-Lion step on one leaf (ref update_fn, :47-59).

    Note the local path uses true ``sign`` (0 → no movement) rather than the
    ±1 vote encoding; this matches the reference exactly.
    """
    p = decay_params(params, lr, wd)
    u = jnp.sign(interp(grad, exp_avg, b1))
    p = p - jnp.asarray(lr, p.dtype) * u.astype(p.dtype)
    m = momentum_update(grad, exp_avg, b2)
    return p, m
