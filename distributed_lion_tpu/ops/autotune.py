"""Kernel autotune subsystem: device-keyed tile search with a persistent cache.

ROADMAP item 1's remaining levers are all TILE choices — flash-attention
fwd/bwd blocking, splash blocking, the Pallas lion ``row_block``, the vocab
chunk count, the vote-bucket count — and until now every one of them was a
hand-enumerated shell config in ``scripts/tpu_runbook_auto2.sh``. One bad
hand pick (``flash@1024x1024``) hung remote compile for >14 minutes and ate
a chunk of a TPU window. This module makes tile choice a MEASUREMENT with
three hard properties:

1. **Per-candidate timeout guards.** Every timed trial runs in a child
   process (its own session) under a hard wall-clock budget covering BOTH
   compile and run; on expiry the whole process group is SIGKILLed and the
   candidate is recorded as a timeout row. A pathological tile can cost one
   budget, never a window (:func:`run_trial_child`, the same process-group
   teardown discipline as ``bench.run_child``).
2. **Deterministic winner selection.** Candidates are generated in a fixed
   order (ascending block sizes — the smaller-VMEM-footprint tile first);
   the winner is the minimum measured ms with ties broken by generation
   order (:func:`select_winner`). Re-running the tuner over identical
   measurements reproduces the identical cache.
3. **A persistent, device-keyed cache.** Winners land in a strict-schema
   JSON document (``scripts/tuning_cache.json``) keyed by
   ``device_kind × knob × shape × dtype``. A cache produced on one device
   kind can never leak onto another (the key embeds
   ``jax.devices()[0].device_kind``); a corrupt or schema-violating cache
   is reported LOUDLY on stderr and treated as absent — defaults win, the
   run proceeds (:func:`load_cache`, :func:`validate_cache_doc` — the same
   strictness contract as ``scripts/validate_metrics.py``, which also
   validates the artifact in CI).

Resolution (the ONE resolver consulted by ``ops/attention`` ``auto``
dispatch, ``train/loop``'s ``kernel='auto'``/``vote_buckets`` auto, and
``bench.py``/``scripts/bench_sweep.py`` row provenance) is
:func:`lookup` — exact key first, then the ``"*"`` wildcard shape (written
by operators, never by the tuner). Elections are pinned bit-identical
tuned-vs-default (tests/test_autotune.py): every knob here changes WHERE
and WHEN work happens, never what is elected.

This module imports nothing heavier than the stdlib at import time, so
``scripts/check_evidence.py`` can validate the cache artifact without jax
(the same loadable-by-file-path discipline as ``train/resilience`` and
``analysis/lint``). jax is imported lazily inside trial execution and
device-kind discovery only.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Optional

CACHE_FORMAT = "dlt-tune-cache-v1"
# repo-layout default (this file lives at distributed_lion_tpu/ops/):
# <repo>/scripts/tuning_cache.json — override with $DLT_TUNE_CACHE
DEFAULT_CACHE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "scripts", "tuning_cache.json",
)

# the tunable surfaces. Each knob's cache value is a flat {str: int} dict
# consumed by exactly one resolver site:
#   flash_tiles    → ops.attention auto dispatch (block_q/kv + bwd tiles)
#   splash_tiles   → ops.attention (explicit splash with no caller tiles)
#   lion_row_block → optim.distributed_lion Pallas kernels (row_block)
#   vocab_chunks   → chunked-CE chunk count (bench/sweep provenance)
#   vote_buckets   → train.loop.resolve_auto_comm (vote_buckets sentinel)
KNOBS = ("flash_tiles", "splash_tiles", "lion_row_block", "vocab_chunks",
         "vote_buckets")

_SEP = "|"
_warned_paths: set = set()
_loaded: dict = {}  # path → entries memo (see load_cache / invalidate_cache)


def cache_path(path: Optional[str] = None) -> str:
    return path or os.environ.get("DLT_TUNE_CACHE") or DEFAULT_CACHE_PATH


def cache_key(device_kind: str, knob: str, shape: str, dtype: str) -> str:
    """``device_kind|knob|shape|dtype`` — the device kind is PART OF the
    key, so entries measured on one accelerator can never resolve on
    another (the device-key-mismatch-ignored contract)."""
    for part in (device_kind, knob, shape, dtype):
        if _SEP in part:
            raise ValueError(f"cache key part {part!r} contains {_SEP!r}")
    return _SEP.join((device_kind, knob, shape, dtype))


# ------------------------------------------------------------ strict schema

def validate_cache_doc(doc) -> list:
    """Violation strings (empty = valid) — the validate_metrics.py-style
    strict contract for the tuning-cache artifact. Checked by the loader
    (violations → loud fallback to defaults), by run_tune before every
    write, and by scripts/validate_metrics.py in CI."""
    errors: list = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, not an object"]
    if doc.get("format") != CACHE_FORMAT:
        errors.append(f"format is {doc.get('format')!r}, want {CACHE_FORMAT!r}")
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        return errors + ["'entries' must be an object"]
    for key, e in entries.items():
        parts = key.split(_SEP)
        if len(parts) != 4 or not all(parts):
            errors.append(f"entries[{key!r}]: key is not "
                          "device_kind|knob|shape|dtype")
            continue
        if parts[1] not in KNOBS:
            errors.append(f"entries[{key!r}]: unknown knob {parts[1]!r}")
        if not isinstance(e, dict):
            errors.append(f"entries[{key!r}]: entry is not an object")
            continue
        val = e.get("value")
        if not isinstance(val, dict) or not val or not all(
                isinstance(k, str) and isinstance(v, int)
                and not isinstance(v, bool) for k, v in val.items()):
            errors.append(f"entries[{key!r}]: 'value' must be a non-empty "
                          "{str: int} object")
        ms = e.get("ms")
        if not isinstance(ms, (int, float)) or isinstance(ms, bool) \
                or not ms == ms or ms < 0:
            errors.append(f"entries[{key!r}]: 'ms' must be a finite "
                          "non-negative number")
    return errors


def load_cache(path: Optional[str] = None) -> dict:
    """entries dict from the cache artifact, or {} when absent. A corrupt
    or schema-violating cache is LOUD (stderr, once per path per process)
    and treated as absent: tuning is an optimization, so every failure
    mode degrades to the built-in defaults rather than blocking a run —
    but never silently."""
    p = cache_path(path)
    if p in _loaded:
        # memoized per process: the resolver runs at trace time (attention
        # auto dispatch), and a re-read per trace would be both wasteful
        # and a trace-determinism hazard if the file changed mid-run.
        # run_tune/tests call invalidate_cache() after writing.
        return _loaded[p]
    try:
        with open(p) as f:
            doc = json.load(f, parse_constant=lambda name: (_ for _ in ()).throw(
                ValueError(f"non-finite JSON constant {name!r}")))
    except FileNotFoundError:
        _loaded[p] = {}
        return {}
    except (OSError, ValueError) as e:
        if p not in _warned_paths:
            _warned_paths.add(p)
            print(f"[autotune] tuning cache {p} unreadable ({e}); "
                  "FALLING BACK to built-in defaults", file=sys.stderr)
        _loaded[p] = {}
        return {}
    errors = validate_cache_doc(doc)
    if errors:
        if p not in _warned_paths:
            _warned_paths.add(p)
            print(f"[autotune] tuning cache {p} fails schema validation "
                  f"({errors[0]}{' ...' if len(errors) > 1 else ''}); "
                  "FALLING BACK to built-in defaults", file=sys.stderr)
        _loaded[p] = {}
        return {}
    _loaded[p] = doc["entries"]
    return doc["entries"]


def invalidate_cache(path: Optional[str] = None) -> None:
    """Drop the load memo (and the warn-once latch) for ``path`` — or for
    every path when None. Call after writing the cache file."""
    if path is None:
        _loaded.clear()
        _warned_paths.clear()
    else:
        _loaded.pop(cache_path(path), None)
        _warned_paths.discard(cache_path(path))


def save_cache(entries: dict, path: Optional[str] = None) -> str:
    """Write {format, entries} atomically (tmp+rename, sorted keys, strict
    JSON) after re-validating — a tuner bug can never commit an artifact
    the loader would then loudly reject."""
    doc = {"format": CACHE_FORMAT, "entries": dict(sorted(entries.items()))}
    errors = validate_cache_doc(doc)
    if errors:
        raise ValueError(f"refusing to write invalid cache: {errors}")
    p = cache_path(path)
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    tmp = p + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, allow_nan=False)
        f.write("\n")
    os.replace(tmp, p)
    invalidate_cache(p)  # the next lookup must see what was just written
    return p


# --------------------------------------------------------------- resolution

_device_kind_cache: Optional[str] = None


def current_device_kind() -> str:
    """``jax.devices()[0].device_kind`` (e.g. ``"TPU v5 lite"``, ``"cpu"``),
    memoized — the lazy jax import keeps this module stdlib-only for
    non-jax consumers (check_evidence, validate_metrics)."""
    global _device_kind_cache
    if _device_kind_cache is None:
        import jax

        _device_kind_cache = jax.devices()[0].device_kind
    return _device_kind_cache


def lookup(knob: str, shape: str, dtype: str, *,
           device_kind: Optional[str] = None,
           path: Optional[str] = None) -> Optional[dict]:
    """THE resolver: the tuned value dict for (device, knob, shape, dtype)
    or None. Exact shape key first, then the ``"*"`` wildcard shape (an
    operator escape hatch — the tuner itself only writes exact shapes,
    keeping every cached number a per-shape measurement, this repo's
    standing rule for tile generalization). Entries keyed to a different
    device kind are invisible by construction."""
    entries = load_cache(path)
    if not entries:
        return None
    dk = device_kind if device_kind is not None else current_device_kind()
    for s in (shape, "*"):
        e = entries.get(cache_key(dk, knob, s, dtype))
        if e is not None:
            return e["value"]
    return None


def attn_shape_key(t: int, head_dim: int) -> str:
    """Flash/splash tile keys vary over the tile-relevant dims only:
    sequence length and head_dim (batch×heads just scale the grid)."""
    return f"T{t}xD{head_dim}"


def resolve_attn_spec(spec: str, *, t: int, head_dim: int, dtype: str,
                      device_kind: Optional[str] = None,
                      path: Optional[str] = None) -> str:
    """``"auto"`` → the cache-tuned explicit spec (``flash@BQxBKV[@BQBxBKVB]``)
    when a flash_tiles entry exists for this device/shape/dtype, else
    ``spec`` unchanged. The provenance form of the same resolution
    ``ops.attention.attention`` applies at dispatch — bench.py records it
    in its row so a sweep log says what ``auto`` MEANT on that device."""
    if spec != "auto":
        return spec
    v = lookup("flash_tiles", attn_shape_key(t, head_dim), dtype,
               device_kind=device_kind, path=path)
    if not v:
        return spec
    # .get with 0-defaults, not [..]: the schema admits partial entries —
    # an operator-written bwd-only pin ({"block_q_bwd": …}) is a supported
    # dispatch case (ops/attention honors it the same way), and the two
    # consumers of the one resolver must agree on every cache entry.
    # 0 means "kernel default" in the spec grammar exactly as in the
    # attention kwargs, so flash@0x0@256x512 round-trips through
    # parse_attn_spec to the identical tile tuple.
    out = f"flash@{v.get('block_q', 0)}x{v.get('block_kv', 0)}"
    if v.get("block_q_bwd") or v.get("block_kv_bwd"):
        out += f"@{v.get('block_q_bwd', 0)}x{v.get('block_kv_bwd', 0)}"
    return out


# ------------------------------------------------------ candidate generation

def tile_candidates(knob: str, info: dict) -> list:
    """The fixed, ordered candidate list for one knob at one shape.
    Ordering is load-bearing: ascending sizes, and :func:`select_winner`
    breaks ms ties by list position — so ties resolve to the SMALLEST
    tile (least VMEM pressure), deterministically."""
    if knob in ("flash_tiles", "splash_tiles"):
        t = int(info["t"])
        sizes = [s for s in (128, 256, 512, 1024) if s <= max(t, 128)]
        cands = [{"block_q": bq, "block_kv": bkv}
                 for bq in sizes for bkv in sizes]
        # flash@1024x1024 hung remote compile >14 min in round 3; keep it
        # OUT of the default grid — the timeout guard would absorb it, but
        # a known-bad tile should not burn a budget on every device
        return [c for c in cands
                if not (c["block_q"] == 1024 and c["block_kv"] == 1024)]
    if knob == "flash_tiles_bwd":  # phase 2 of the flash search (run_tune)
        t = int(info["t"])
        sizes = [s for s in (128, 256, 512, 1024) if s <= max(t, 128)]
        return [{"block_q_bwd": bq, "block_kv_bwd": bkv}
                for bq in sizes for bkv in sizes]
    if knob == "lion_row_block":
        return [{"row_block": rb} for rb in (128, 256, 512, 1024, 2048)]
    if knob == "vocab_chunks":
        v = int(info["v"])
        return [{"vocab_chunks": c} for c in (1, 2, 4, 8, 16, 32) if c <= v]
    if knob == "vote_buckets":
        return [{"vote_buckets": b} for b in (1, 2, 4, 8, 16)]
    raise ValueError(f"unknown knob {knob!r}")


def select_winner(results: list) -> Optional[dict]:
    """Deterministic winner from trial results
    (``[{"candidate", "ms"|None, "error"|None}, ...]`` in candidate order):
    minimum ms, ties broken by candidate order (earlier = smaller tile
    wins). None when no candidate produced a measurement."""
    best = None
    for idx, r in enumerate(results):
        ms = r.get("ms")
        if ms is None:
            continue
        if best is None or ms < best[0]:
            best = (ms, idx, r)
    if best is None:
        return None
    return {"candidate": best[2]["candidate"], "ms": best[0],
            "index": best[1]}


# ------------------------------------------------------------- timed trials

def _time_jitted(fn, args, iters: int) -> float:
    """min wall ms over ``iters`` calls after one warmup (compile) call.
    The warmup's block_until_ready keeps compile out of the timed window;
    min (not mean) because scheduler noise only ever ADDS time."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def execute_trial(payload: dict) -> dict:
    """Run ONE candidate measurement in-process and return
    ``{"ms": float}`` or ``{"error": str}``. Called inside the
    timeout-guarded child (``run_tune --trial``) on hardware, or directly
    via ``run_tune --in-process`` where child-spawn latency dominates
    (CPU CI). ``_test_sleep_s`` is the timeout-guard test hook: it stalls
    the trial exactly like a wedged compile would, so tests can prove the
    guard kills a slow candidate without needing a real pathological tile.
    """
    if payload.get("_test_sleep_s"):
        time.sleep(float(payload["_test_sleep_s"]))
    if payload.get("knob") == "_probe":
        # backend discovery for the ORCHESTRATOR, run as a guarded child:
        # the parent must never initialize jax itself in child mode — on
        # TPU it would take the libtpu single-client lock and every trial
        # child would then fail to open the chip (the bench.py orchestrator
        # lesson, bench.py:590-596). "ms" 0.0 satisfies the child-result
        # shape contract of run_trial_child.
        import jax

        return {"ms": 0.0, "backend": jax.default_backend(),
                "device_kind": jax.devices()[0].device_kind}
    knob, cand, info = payload["knob"], payload["candidate"], payload["info"]
    iters = int(payload.get("iters", 5))
    import jax
    import jax.numpy as jnp

    on_tpu = jax.default_backend() == "tpu"
    dtype = jnp.dtype(info.get("dtype", "float32"))
    try:
        if knob in ("flash_tiles", "flash_tiles_bwd", "splash_tiles"):
            if not on_tpu:
                return {"error": "unsupported: Pallas attention kernels "
                                 "need a TPU backend (xla fallback has no "
                                 "tiles to tune)"}
            from distributed_lion_tpu.ops.attention import (
                attention_flash,
                attention_splash,
            )

            b, h, t, d = (int(info[k]) for k in ("b", "h", "t", "d"))
            ks = jax.random.split(jax.random.key(0), 3)
            q, k, v = (jax.random.normal(kk, (b, h, t, d), dtype) for kk in ks)
            if knob == "splash_tiles":
                def fwd(q, k, v):
                    return attention_splash(q, k, v, **cand)
            else:
                tiles = dict(info.get("base", {}))
                tiles.update(cand)

                def fwd(q, k, v):
                    return attention_flash(q, k, v, **tiles)

            step = jax.jit(jax.grad(
                lambda q, k, v: fwd(q, k, v).astype(jnp.float32).sum(),
                argnums=(0, 1, 2)))
            return {"ms": _time_jitted(step, (q, k, v), iters)}

        if knob == "lion_row_block":
            from distributed_lion_tpu.ops.pallas_lion import (
                fused_apply,
                fused_ballots,
                pallas_available,
            )

            n = int(info["n"])
            interpret = not pallas_available()
            key = jax.random.key(0)
            g = jax.random.normal(key, (n,), dtype)
            m = jnp.zeros((n,), dtype)
            p = jax.random.normal(jax.random.fold_in(key, 1), (n,), dtype)

            @jax.jit
            def step(p, g, m):
                ballots = fused_ballots(g, m, 0.9, interpret=interpret,
                                        row_block=cand["row_block"])
                return fused_apply(p, g, m, ballots.astype(jnp.int32),
                                   1e-4, 0.1, 0.99, interpret=interpret,
                                   row_block=cand["row_block"])

            return {"ms": _time_jitted(step, (p, g, m), iters)}

        if knob == "vocab_chunks":
            from distributed_lion_tpu.ops.xent import chunked_softmax_xent

            n, d, v = (int(info[k]) for k in ("n", "d", "v"))
            key = jax.random.key(0)
            hidden = jax.random.normal(key, (n, d), dtype)
            emb = jax.random.normal(jax.random.fold_in(key, 1), (v, d), dtype)
            labels = jnp.arange(n, dtype=jnp.int32) % v

            @jax.jit
            def step(hidden, emb):
                nll, _ = chunked_softmax_xent(
                    hidden, emb, labels, n_chunks=cand["vocab_chunks"])
                return jax.grad(
                    lambda h, e: chunked_softmax_xent(
                        h, e, labels,
                        n_chunks=cand["vocab_chunks"])[0].sum(),
                    argnums=(0, 1))(hidden, emb)

            return {"ms": _time_jitted(step, (hidden, emb), iters)}

        if knob == "vote_buckets":
            # single-host proxy: the bucket pipeline's per-bucket kernel
            # launches + window slicing at B buckets over an n-coordinate
            # ballot. The WIRE overlap itself is only measurable multi-chip
            # (the runbook's overlap ablation owns that number); this trial
            # ranks the launch-amortization side, which is what auto's B
            # controls on a given ballot size.
            from distributed_lion_tpu.ops.codec import bucket_bounds
            from distributed_lion_tpu.ops.pallas_lion import (
                fused_apply_window,
                fused_ballots_window,
                pallas_available,
            )

            n = int(info["n"])
            interpret = not pallas_available()
            bounds = bucket_bounds(n, cand["vote_buckets"], 1, "sign_psum")
            key = jax.random.key(0)
            g = jax.random.normal(key, (n,), dtype)
            m = jnp.zeros((n,), dtype)
            p = jax.random.normal(jax.random.fold_in(key, 1), (n,), dtype)

            @jax.jit
            def step(p, g, m):
                outs = []
                for start, ln in bounds:
                    ballots = fused_ballots_window(
                        g, m, 0.9, start=start, length=ln,
                        interpret=interpret)
                    outs.append(fused_apply_window(
                        p, g, m, ballots.astype(jnp.int32), 1e-4, 0.1, 0.99,
                        start=start, length=ln, interpret=interpret))
                return outs

            return {"ms": _time_jitted(step, (p, g, m), iters)}
    except Exception as e:  # a failed candidate is a ROW, not a crash:
        # the search must survive OOM/unsupported-tile errors per candidate
        return {"error": f"{type(e).__name__}: {e}"}
    return {"error": f"unknown knob {knob!r}"}


# ------------------------------------------------- the per-candidate guard

_trial_child: Optional[subprocess.Popen] = None


def _kill_trial_child() -> None:
    if _trial_child is not None and _trial_child.poll() is None:
        try:
            os.killpg(_trial_child.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def run_trial_child(payload: dict, timeout_s: float,
                    python: Optional[str] = None, journal=None) -> dict:
    """Run one trial in a child process under a HARD wall-clock budget
    covering compile AND run — the guard that makes a pathological tile
    cost one ``timeout_s``, never a window. The child runs in its own
    session; on expiry the whole process group is SIGKILLed (a wedged XLA
    compile ignores SIGTERM). Returns the child's JSON result, an
    ``{"error": "timeout ..."}`` row, or an ``{"error": "rc=..."}`` row —
    the search always continues.

    ``journal`` (train/journal.py's recorder, duck-typed so this module
    stays stdlib-only at import) gets one ``autotune/trial`` span per
    candidate — knob, candidate, measured ms or error, and the child's
    wall time including compile — so a tuning session's time budget is
    attributable candidate by candidate."""
    t_trial = time.monotonic()
    result = _run_trial_child(payload, timeout_s, python)
    journal_trial(journal, str(payload.get("knob")),
                  payload.get("candidate", {}), result, t_trial)
    return result


def journal_trial(journal, knob: str, candidate: dict, result: dict,
                  t0: float) -> None:
    """THE one autotune/trial span writer (run_trial_child and run_tune's
    in-process branch share it, so the record shape cannot drift). Flushes
    after every trial: a killed tuner must still leave a legible journal,
    the same discipline as the per-row stdout printing. Journaling errors
    warn and never break the search."""
    if journal is None:
        return
    try:
        journal.record({
            "kind": "span", "name": "autotune/trial",
            "dur": round(time.monotonic() - t0, 6),
            "knob": knob,
            "candidate": json.dumps(candidate, sort_keys=True,
                                    allow_nan=False),
            "ms": result.get("ms"), "error": result.get("error"),
        })
        flush = getattr(journal, "flush", None)
        if flush is not None:
            flush()
    except Exception as e:  # journaling must never break the search
        print(f"[autotune] journal record failed: {e}", file=sys.stderr)


def _run_trial_child(payload: dict, timeout_s: float,
                     python: Optional[str] = None) -> dict:
    global _trial_child
    cmd = [python or sys.executable, "-m",
           "distributed_lion_tpu.cli.run_tune", "--trial",
           json.dumps(payload, allow_nan=False)]
    _trial_child = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True)
    try:
        out, err = _trial_child.communicate(timeout=timeout_s)
        rc = _trial_child.returncode
    except subprocess.TimeoutExpired:
        _kill_trial_child()
        _trial_child.wait()
        _trial_child = None
        return {"error": f"timeout after {timeout_s:.0f}s "
                         "(compile/run guard killed the candidate)"}
    _trial_child = None
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(d, dict) and ("ms" in d or "error" in d):
                return d
    tail = (err or out or "").strip().splitlines()[-3:]
    return {"error": (f"rc={rc}: " + " | ".join(tail))[:300]}


def install_trial_teardown() -> None:
    """SIGTERM/exit teardown for the current trial child — an outer driver
    timeout must never orphan a child holding the TPU lock (the bench.py
    lesson, applied to the tuner)."""
    import atexit

    signal.signal(signal.SIGTERM,
                  lambda s, f: (_kill_trial_child(), sys.exit(128 + s)))
    atexit.register(_kill_trial_child)
