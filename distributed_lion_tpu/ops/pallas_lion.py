"""Fused Pallas kernels for the Distributed Lion hot loop.

The reference's optimizer is the per-tensor Python loop SURVEY §3.1 flags as
the main bottleneck (~148 tensors × [sign → pack → all_gather → unpack ×W →
torch.mode → apply] per step; README.md:2 admits it is "currently slow").
Here the whole pytree is one flat vector and the step is two VMEM passes
(SURVEY §7 stage 6):

- :func:`fused_ballots` — one pass over (g, m): ``ballot = ±1 from
  b1*m + (1-b1)*g > 0`` as int8, ready for the on-fabric ``psum`` vote. No
  f32 intermediate ever reaches HBM.
- :func:`fused_apply` — one pass over (p, g, m, vote_total): weight decay,
  elected-sign application, and the momentum update together:
  ``p' = p*(1-lr*wd) - lr*sign(total>0)``; ``m' = b2*m + (1-b2)*g``.

Between the two sits the vote wire — ONE collective, or ``vote_buckets``
pipelined ones: the ``*_window`` entry points run the same kernels over a
static ``[start, start + length)`` window of shared flat buffers, so the
bucketed optimizer slices per-leaf views instead of materializing full flat
copies of params/grads/momentum, and bucket k's collective overlaps bucket
k−1's apply. The kernels are elementwise VPU work tiled (≤ROW_BLOCK, 128)
with dtype-uniform flat inputs; CPU tests run them in interpreter mode
(``interpret=True``).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
ROW_BLOCK = 512  # default rows per grid step → (512, 128) f32 blocks =
# 256 KiB. Every kernel below takes a ``row_block`` override (0 = this
# default) threaded from the autotune cache (ops/autotune, knob
# 'lion_row_block') — tile geometry is a measured perf knob, never a
# numerics knob: outputs are bit-identical at any row_block (pinned by
# tests/test_autotune.py).
MIN_ROWS = 32    # min row granularity: covers the (8,128) f32, (16,128)
# bf16 and (32,128) int8 native tile shapes, so small bucket windows
# compile on hardware without padding all the way to a full ROW_BLOCK


def _resolve_row_block(row_block: int) -> int:
    if row_block == 0:
        return ROW_BLOCK
    if row_block < MIN_ROWS or row_block % MIN_ROWS:
        raise ValueError(
            f"row_block must be a positive multiple of {MIN_ROWS} "
            f"(the int8 native-tile sublane count), got {row_block}")
    return row_block


def _grid_rows(n: int, row_block: int = 0) -> tuple[int, int]:
    """(padded rows, rows per grid step) for an [n] flat operand. Large
    inputs tile at ``row_block`` (default ROW_BLOCK); small ones (per-leaf
    bucket windows) shrink the block to the input instead of zero-padding
    64K elements."""
    rb = _resolve_row_block(row_block)
    rows = max(1, math.ceil(n / LANES))
    rows = math.ceil(rows / MIN_ROWS) * MIN_ROWS
    block = min(rb, rows)
    return math.ceil(rows / block) * block, block


def _pad_to_grid(flat: jnp.ndarray, row_block: int = 0) -> tuple[jnp.ndarray, int]:
    """[n] → [rows, 128] zero-padded to the _grid_rows geometry."""
    n = flat.shape[0]
    rows, _ = _grid_rows(n, row_block)
    pad = rows * LANES - n
    return jnp.pad(flat, (0, pad)).reshape(rows, LANES), n


def _ballot_kernel(b1: float, g_ref, m_ref, out_ref):
    u = m_ref[:].astype(jnp.float32) * b1 + g_ref[:].astype(jnp.float32) * (1.0 - b1)
    out_ref[:] = jnp.where(u > 0, 1, -1).astype(jnp.int8)


def fused_ballots(
    g_flat: jnp.ndarray, m_flat: jnp.ndarray, b1: float, *,
    interpret: bool = False, row_block: int = 0
) -> jnp.ndarray:
    """[n] grads + momentum → [n] int8 ±1 ballots (ref :68-71 semantics:
    zero update votes −1, the ``> 0`` encoding)."""
    g2, n = _pad_to_grid(g_flat, row_block)
    m2, _ = _pad_to_grid(m_flat, row_block)
    rows, block = g2.shape[0], _grid_rows(n, row_block)[1]
    spec = pl.BlockSpec((block, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_ballot_kernel, b1),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.int8),
        grid=(rows // block,),
        in_specs=[spec, spec],
        out_specs=spec,
        interpret=interpret,
    )(g2, m2)
    return out.reshape(-1)[:n]


def _apply_kernel(wd: float, b2: float, lr_ref, p_ref, g_ref, m_ref, tot_ref,
                  p_out, m_out):
    lr = lr_ref[0]
    pdt = p_ref.dtype
    # elected sign: total > 0 → +1, ties/negative → −1 (tie rule SURVEY §2.3)
    s = jnp.where(tot_ref[:] > 0, 1.0, -1.0)
    p32 = p_ref[:].astype(jnp.float32)
    p_out[:] = (p32 * (1.0 - lr * wd) - lr * s).astype(pdt)
    m_out[:] = (
        m_ref[:].astype(jnp.float32) * b2 + g_ref[:].astype(jnp.float32) * (1.0 - b2)
    ).astype(m_ref.dtype)


def fused_apply(
    p_flat: jnp.ndarray,
    g_flat: jnp.ndarray,
    m_flat: jnp.ndarray,
    vote_total: jnp.ndarray,
    lr,
    wd: float,
    b2: float,
    *,
    interpret: bool = False,
    row_block: int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One fused pass: decay + elected update + momentum (ref :64, :91-96)."""
    p2, n = _pad_to_grid(p_flat, row_block)
    g2, _ = _pad_to_grid(g_flat, row_block)
    m2, _ = _pad_to_grid(m_flat, row_block)
    t2, _ = _pad_to_grid(vote_total.astype(jnp.int32), row_block)
    rows, blk = p2.shape[0], _grid_rows(n, row_block)[1]
    lr_arr = jnp.asarray(lr, jnp.float32).reshape(1)
    block = lambda: pl.BlockSpec((blk, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
    p_new, m_new = pl.pallas_call(
        functools.partial(_apply_kernel, wd, b2),
        out_shape=(
            jax.ShapeDtypeStruct((rows, LANES), p_flat.dtype),
            jax.ShapeDtypeStruct((rows, LANES), m_flat.dtype),
        ),
        grid=(rows // blk,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # lr scalar
            block(), block(), block(), block(),
        ],
        out_specs=(block(), block()),
        interpret=interpret,
    )(lr_arr, p2, g2, m2, t2)
    return p_new.reshape(-1)[:n], m_new.reshape(-1)[:n]


def fused_ballots_window(
    g_flat: jnp.ndarray,
    m_flat: jnp.ndarray,
    b1: float,
    *,
    start: int,
    length: int,
    interpret: bool = False,
    row_block: int = 0,
) -> jnp.ndarray:
    """Ballots for the ``[start, start + length)`` window of shared flat
    (g, m) buffers — the per-bucket entry point of the pipelined optimizer
    (optim.distributed_lion). The window is sliced with static bounds, so
    XLA fuses the slice into the kernel's operand pass instead of the old
    path's full-pytree ``jnp.concatenate`` materialization."""
    g_w = jax.lax.slice(g_flat, (start,), (start + length,))
    m_w = jax.lax.slice(m_flat, (start,), (start + length,))
    return fused_ballots(g_w, m_w, b1, interpret=interpret,
                         row_block=row_block)


def fused_apply_window(
    p_flat: jnp.ndarray,
    g_flat: jnp.ndarray,
    m_flat: jnp.ndarray,
    bucket_total: jnp.ndarray,
    lr,
    wd: float,
    b2: float,
    *,
    start: int,
    length: int,
    total_offset: int = 0,
    interpret: bool = False,
    row_block: int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fused decay + elected update + momentum for one window of shared flat
    (p, g, m) buffers against ``bucket_total[total_offset :
    total_offset + length]`` (a single bucket's collective result). Returns
    the window's (p_new, m_new) only — the caller reassembles leaves, and a
    window depends on nothing but ITS bucket's wire, which is what lets the
    bucket-k collective run while bucket k−1 applies."""
    p_w = jax.lax.slice(p_flat, (start,), (start + length,))
    g_w = jax.lax.slice(g_flat, (start,), (start + length,))
    m_w = jax.lax.slice(m_flat, (start,), (start + length,))
    t_w = jax.lax.slice(bucket_total, (total_offset,),
                        (total_offset + length,))
    return fused_apply(p_w, g_w, m_w, t_w, lr, wd, b2, interpret=interpret,
                       row_block=row_block)


def _stats_kernel(w: int, nbins: int, ballot_ref, tot_ref, mask_ref, out_ref):
    """Per-bucket vote-health tallies, accumulated across grid steps into a
    single resident VMEM tile (constant output index map → the buffer
    persists between iterations; initialized at program_id 0). Row 0 lanes
    [0, nbins) hold the margin bincount, row 1 lane 0 the local-ballot
    disagreement count. Binning must match telemetry.margin_hist exactly
    (pinned by test): bin = min(|total| * nbins // w, nbins − 1)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    t = tot_ref[:].astype(jnp.int32)
    m = mask_ref[:] > 0  # zero-padded grid tail must not count
    binidx = jnp.minimum((jnp.abs(t) * nbins) // w, nbins - 1)
    row = jax.lax.broadcasted_iota(jnp.int32, out_ref.shape, 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, out_ref.shape, 1)
    upd = jnp.zeros(out_ref.shape, jnp.int32)
    for b in range(nbins):  # static unroll: nbins full-tile VPU reductions
        cnt = jnp.sum(jnp.where(m & (binidx == b), 1, 0))
        upd = upd + jnp.where((row == 0) & (lane == b), cnt, 0)
    dis = jnp.sum(jnp.where(m & ((ballot_ref[:] > 0) != (t > 0)), 1, 0))
    upd = upd + jnp.where((row == 1) & (lane == 0), dis, 0)
    out_ref[...] = out_ref[...] + upd


def bucket_vote_stats(
    ballot: jnp.ndarray,
    total: jnp.ndarray,
    world: int,
    nbins: int,
    *,
    interpret: bool = False,
    row_block: int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One bucket's vote-health tallies from its int8 ballots and the
    bucket's collective result: ``(margin bincount i32[nbins], local
    disagreement count i32)`` — the per-bucket telemetry emitted by the
    window-kernel optimizer path (optim.distributed_lion telemetry mode).
    Reads arrays the bucket pipeline already has in VMEM; never touches
    what is elected. Margin bins are only meaningful when ``total`` is an
    exact tally (the caller zeroes the histogram for ±1-proxy wires)."""
    b2, n = _pad_to_grid(ballot.astype(jnp.int8), row_block)
    t2, _ = _pad_to_grid(total.astype(jnp.int32), row_block)
    m2, _ = _pad_to_grid(jnp.ones((n,), jnp.int32), row_block)
    rows, block = b2.shape[0], _grid_rows(n, row_block)[1]
    spec = lambda: pl.BlockSpec((block, LANES), lambda i: (i, 0),  # noqa: E731
                                memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_stats_kernel, world, nbins),
        out_shape=jax.ShapeDtypeStruct((8, LANES), jnp.int32),
        grid=(rows // block,),
        in_specs=[spec(), spec(), spec()],
        out_specs=pl.BlockSpec((8, LANES), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(b2, t2, m2)
    return out[0, :nbins], out[1, 0]


def pallas_available() -> bool:
    return jax.default_backend() == "tpu"


def resolve_kernel_mode(kernel: str) -> Optional[bool]:
    """'auto' → pallas on TPU, XLA elsewhere; 'pallas' forces (interpreted on
    CPU — for tests); 'xla' disables. Returns interpret flag or None for
    the XLA path."""
    if kernel == "xla":
        return None
    if kernel == "pallas":
        return not pallas_available()
    if kernel == "auto":
        return False if pallas_available() else None
    raise ValueError(f"unknown kernel mode {kernel!r}")
