"""Frozen-weight quantization: NF4 (QLoRA-style) and int8, blockwise.

The reference's 7B workloads load the base model in 4-bit NF4 with bf16
compute via bitsandbytes (/root/reference/sft_llama2.py:141-153,
dpo_llama2.py:133-152: BitsAndBytesConfig(load_in_4bit, nf4, bf16)). Here the
same capability is native JAX:

- :class:`QuantizedTensor` — a pytree-registered container of packed codes +
  per-block absmax scales; drops into any weight slot, and the model's
  ``maybe_dequant`` dequantizes on the fly inside the matmul's producer
  fusion (XLA fuses dequant into the MXU feed; no persistent dense copy).
- NF4: the 16-level normal-quantile codebook, two codes packed per uint8 →
  0.5 byte/param + absmax overhead, matching bitsandbytes' storage.
- int8: blockwise absmax, 1 byte/param — faster dequant, looser.

Quantized leaves are for FROZEN weights (LoRA bases, DPO reference models).
They are excluded from gradient/optimizer trees by construction (see
models/lora.py split_lora_params).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# The 16 NF4 levels: quantiles of N(0,1) rescaled to [-1, 1] (the QLoRA
# codebook, reproduced numerically — same values bitsandbytes ships).
NF4_LEVELS = np.asarray(
    [
        -1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
        -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
        0.07958029955625534, 0.16093020141124725, 0.24611230194568634,
        0.33791524171829224, 0.44070982933044434, 0.5626170039176941,
        0.7229568362236023, 1.0,
    ],
    np.float32,
)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    codes: jnp.ndarray      # packed uint8 (nf4: 2 codes/byte; int8: 1 code/byte)
    absmax: jnp.ndarray     # f32 [n_blocks] per-block scale
    shape: tuple            # original dense shape (static)
    fmt: str                # 'nf4' | 'int8' (static)
    block: int              # block size in elements (static)

    def tree_flatten(self):
        return (self.codes, self.absmax), (self.shape, self.fmt, self.block)

    @classmethod
    def tree_unflatten(cls, aux, children):
        codes, absmax = children
        shape, fmt, block = aux
        return cls(codes, absmax, shape, fmt, block)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape))

    @property
    def ndim(self) -> int:
        return len(self.shape)


def quantize_nf4(w: jnp.ndarray, block: int = 64) -> QuantizedTensor:
    """Blockwise absmax NF4 quantization (nearest codebook level)."""
    shape = tuple(w.shape)
    flat = jnp.ravel(w).astype(jnp.float32)
    n = flat.shape[0]
    pad = (-n) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    blocks = flat.reshape(-1, block)
    absmax = jnp.abs(blocks).max(axis=1)
    scaled = blocks / jnp.maximum(absmax, 1e-12)[:, None]
    # nearest level via midpoint bisection — O(n log 16) and no [n, 16]
    # distance tensor (which would be 64 transient bytes/param at 7B scale)
    mids = jnp.asarray((NF4_LEVELS[1:] + NF4_LEVELS[:-1]) / 2.0)
    codes4 = jnp.searchsorted(mids, scaled).astype(jnp.uint8).reshape(-1)
    packed = (codes4[0::2] | (codes4[1::2] << 4)).astype(jnp.uint8)
    return QuantizedTensor(packed, absmax, shape, "nf4", block)


def quantize_int8(w: jnp.ndarray, block: int = 256) -> QuantizedTensor:
    shape = tuple(w.shape)
    flat = jnp.ravel(w).astype(jnp.float32)
    n = flat.shape[0]
    pad = (-n) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    blocks = flat.reshape(-1, block)
    absmax = jnp.abs(blocks).max(axis=1)
    q = jnp.round(blocks / jnp.maximum(absmax, 1e-12)[:, None] * 127.0)
    codes = (q.astype(jnp.int8).view(jnp.uint8)).reshape(-1)
    return QuantizedTensor(codes, absmax, shape, "int8", block)


def dequantize(qt: QuantizedTensor, dtype=jnp.bfloat16) -> jnp.ndarray:
    if qt.fmt == "nf4":
        lo = qt.codes & 0x0F
        hi = qt.codes >> 4
        codes4 = jnp.stack([lo, hi], axis=1).reshape(-1)
        levels = jnp.asarray(NF4_LEVELS)[codes4]
        vals = levels.reshape(-1, qt.block) * qt.absmax[:, None]
    elif qt.fmt == "int8":
        q = qt.codes.view(jnp.int8).astype(jnp.float32)
        vals = q.reshape(-1, qt.block) * (qt.absmax[:, None] / 127.0)
    else:
        raise ValueError(f"unknown quant format {qt.fmt!r}")
    return vals.reshape(-1)[: qt.size].reshape(qt.shape).astype(dtype)


def maybe_dequant(w: Any, dtype=jnp.bfloat16):
    """Models call this on every weight: dense arrays pass through."""
    if isinstance(w, QuantizedTensor):
        return dequantize(w, dtype)
    return w


def quantize_tree(params: Any, fmt: str = "nf4", min_size: int = 4096,
                  block: int | None = None) -> Any:
    """Quantize every large 2-D+ weight leaf of a pytree (small leaves —
    norms, biases — stay dense, mirroring bitsandbytes' module targeting)."""
    quant = {"nf4": quantize_nf4, "int8": quantize_int8}[fmt]
    kw = {} if block is None else {"block": block}

    def leaf(w):
        if isinstance(w, QuantizedTensor):
            return w
        if getattr(w, "ndim", 0) >= 2 and w.size >= min_size:
            return quant(w, **kw)
        return w

    return jax.tree.map(leaf, params, is_leaf=lambda x: isinstance(x, QuantizedTensor))


def dequantize_tree(params: Any, dtype=jnp.float32) -> Any:
    """Dense copy of a tree with quantized leaves (for export/merge-save)."""
    return jax.tree.map(
        lambda w: dequantize(w, dtype) if isinstance(w, QuantizedTensor) else w,
        params,
        is_leaf=lambda x: isinstance(x, QuantizedTensor),
    )
