"""Frozen-weight quantization: NF4 (QLoRA-style) and int8, blockwise.

The reference's 7B workloads load the base model in 4-bit NF4 with bf16
compute via bitsandbytes (/root/reference/sft_llama2.py:141-153,
dpo_llama2.py:133-152: BitsAndBytesConfig(load_in_4bit, nf4, bf16)). Here the
same capability is native JAX:

- :class:`QuantizedTensor` — a pytree-registered container of packed codes +
  per-block absmax scales; drops into any weight slot, and the model's
  ``maybe_dequant`` dequantizes on the fly inside the matmul's producer
  fusion (XLA fuses dequant into the MXU feed; no persistent dense copy).
- NF4: the 16-level normal-quantile codebook, two codes packed per uint8 →
  0.5 byte/param + absmax overhead, matching bitsandbytes' storage.
- int8: blockwise absmax, 1 byte/param — faster dequant, looser.

Quantized leaves are for FROZEN weights (LoRA bases, DPO reference models).
They are excluded from gradient/optimizer trees by construction (see
models/lora.py split_lora_params).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# The 16 NF4 levels: quantiles of N(0,1) rescaled to [-1, 1] (the QLoRA
# codebook, reproduced numerically — same values bitsandbytes ships).
NF4_LEVELS = np.asarray(
    [
        -1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
        -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
        0.07958029955625534, 0.16093020141124725, 0.24611230194568634,
        0.33791524171829224, 0.44070982933044434, 0.5626170039176941,
        0.7229568362236023, 1.0,
    ],
    np.float32,
)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    """Packed codes + per-block absmax scales.

    Two storage layouts:

    - ``shaped`` (default when the last dim divides the block size): blocks
      run along the LAST dim only, and codes/absmax keep the dense weight's
      rank — codes is ``[..., last/2]`` (nf4) or ``[..., last]`` (int8),
      absmax is ``[..., last/block]``. Because every leading dim is 1:1 with
      the dense weight and last-dim blocks never straddle a slice boundary,
      the SAME PartitionSpec that shards the dense weight shards the
      quantized leaf — this is what makes ``--quant nf4`` compose with
      tensor parallelism (each rank dequantizes only its shard).
    - ``flat``: the fallback for odd shapes — codes is 1-D over the
      row-major flattened (padded) weight. Not shardable along weight dims.
    """

    codes: jnp.ndarray      # packed uint8 (nf4: 2 codes/byte; int8: 1 code/byte)
    absmax: jnp.ndarray     # f32 per-block scale
    shape: tuple            # original dense GLOBAL shape (static)
    fmt: str                # 'nf4' | 'int8' (static)
    block: int              # block size in elements (static)
    layout: str = "shaped"  # 'shaped' | 'flat' (static)

    def tree_flatten(self):
        return (self.codes, self.absmax), (self.shape, self.fmt, self.block,
                                           self.layout)

    @classmethod
    def tree_unflatten(cls, aux, children):
        codes, absmax = children
        return cls(codes, absmax, *aux)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape))

    @property
    def ndim(self) -> int:
        return len(self.shape)


def _use_shaped(shape: tuple, block: int, fmt: str) -> bool:
    # nf4 packs 2 codes/byte along the last dim, so it additionally needs an
    # even block; int8 has no packing constraint.
    return (len(shape) >= 2 and shape[-1] % block == 0
            and (fmt != "nf4" or block % 2 == 0))


def _nf4_codes(blocks: jnp.ndarray, absmax: jnp.ndarray) -> jnp.ndarray:
    """[..., block] f32 + [...] absmax → [..., block] uint8 4-bit codes,
    nearest level via midpoint bisection — O(n log 16) and no [n, 16]
    distance tensor (which would be 64 transient bytes/param at 7B scale)."""
    scaled = blocks / jnp.maximum(absmax, 1e-12)[..., None]
    mids = jnp.asarray((NF4_LEVELS[1:] + NF4_LEVELS[:-1]) / 2.0)
    return jnp.searchsorted(mids, scaled).astype(jnp.uint8)


def quantize_nf4(w: jnp.ndarray, block: int = 64) -> QuantizedTensor:
    """Blockwise absmax NF4 quantization (nearest codebook level).

    Blocks run along the last dim when it divides ``block`` (the shaped,
    TP-shardable layout — identical numerics to the flat layout for such
    shapes, since row-major flat blocks never straddled rows anyway)."""
    shape = tuple(w.shape)
    if _use_shaped(shape, block, "nf4"):
        blocks = w.astype(jnp.float32).reshape(
            shape[:-1] + (shape[-1] // block, block))
        absmax = jnp.abs(blocks).max(axis=-1)
        codes4 = _nf4_codes(blocks, absmax).reshape(shape)
        packed = (codes4[..., 0::2] | (codes4[..., 1::2] << 4)).astype(jnp.uint8)
        return QuantizedTensor(packed, absmax, shape, "nf4", block, "shaped")
    flat = jnp.ravel(w).astype(jnp.float32)
    pad = (-flat.shape[0]) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    blocks = flat.reshape(-1, block)
    absmax = jnp.abs(blocks).max(axis=1)
    codes4 = _nf4_codes(blocks, absmax).reshape(-1)
    packed = (codes4[0::2] | (codes4[1::2] << 4)).astype(jnp.uint8)
    return QuantizedTensor(packed, absmax, shape, "nf4", block, "flat")


def quantize_int8(w: jnp.ndarray, block: int = 256) -> QuantizedTensor:
    shape = tuple(w.shape)
    if _use_shaped(shape, block, "int8"):
        blocks = w.astype(jnp.float32).reshape(
            shape[:-1] + (shape[-1] // block, block))
        absmax = jnp.abs(blocks).max(axis=-1)
        q = jnp.round(blocks / jnp.maximum(absmax, 1e-12)[..., None] * 127.0)
        codes = q.astype(jnp.int8).view(jnp.uint8).reshape(shape)
        return QuantizedTensor(codes, absmax, shape, "int8", block, "shaped")
    flat = jnp.ravel(w).astype(jnp.float32)
    pad = (-flat.shape[0]) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    blocks = flat.reshape(-1, block)
    absmax = jnp.abs(blocks).max(axis=1)
    q = jnp.round(blocks / jnp.maximum(absmax, 1e-12)[:, None] * 127.0)
    codes = (q.astype(jnp.int8).view(jnp.uint8)).reshape(-1)
    return QuantizedTensor(codes, absmax, shape, "int8", block, "flat")


def dequantize(qt: QuantizedTensor, dtype=jnp.bfloat16) -> jnp.ndarray:
    if qt.layout == "shaped":
        # LOCAL dense shape derives from the codes actually present — under
        # shard_map each rank holds a slice and dequantizes just that slice.
        lead = tuple(qt.codes.shape[:-1])
        if qt.fmt == "nf4":
            lo = qt.codes & 0x0F
            hi = qt.codes >> 4
            last = qt.codes.shape[-1] * 2
            codes4 = jnp.stack([lo, hi], axis=-1).reshape(lead + (last,))
            levels = jnp.asarray(NF4_LEVELS)[codes4]
            vals = (levels.reshape(lead + (last // qt.block, qt.block))
                    * qt.absmax[..., None])
        elif qt.fmt == "int8":
            last = qt.codes.shape[-1]
            q = qt.codes.view(jnp.int8).astype(jnp.float32)
            vals = (q.reshape(lead + (last // qt.block, qt.block))
                    * (qt.absmax[..., None] / 127.0))
        else:
            raise ValueError(f"unknown quant format {qt.fmt!r}")
        return vals.reshape(lead + (last,)).astype(dtype)
    if qt.fmt == "nf4":
        lo = qt.codes & 0x0F
        hi = qt.codes >> 4
        codes4 = jnp.stack([lo, hi], axis=1).reshape(-1)
        levels = jnp.asarray(NF4_LEVELS)[codes4]
        vals = levels.reshape(-1, qt.block) * qt.absmax[:, None]
    elif qt.fmt == "int8":
        q = qt.codes.view(jnp.int8).astype(jnp.float32)
        vals = q.reshape(-1, qt.block) * (qt.absmax[:, None] / 127.0)
    else:
        raise ValueError(f"unknown quant format {qt.fmt!r}")
    return vals.reshape(-1)[: qt.size].reshape(qt.shape).astype(dtype)


def maybe_dequant(w: Any, dtype=jnp.bfloat16):
    """Models call this on every weight: dense arrays pass through."""
    if isinstance(w, QuantizedTensor):
        return dequantize(w, dtype)
    return w


def quantize_tree(params: Any, fmt: str = "nf4", min_size: int = 4096,
                  block: int | None = None) -> Any:
    """Quantize every large 2-D+ weight leaf of a pytree (small leaves —
    norms, biases — stay dense, mirroring bitsandbytes' module targeting)."""
    quant = {"nf4": quantize_nf4, "int8": quantize_int8}[fmt]
    kw = {} if block is None else {"block": block}

    def leaf(w):
        if isinstance(w, QuantizedTensor):
            return w
        if getattr(w, "ndim", 0) >= 2 and w.size >= min_size:
            return quant(w, **kw)
        return w

    return jax.tree.map(leaf, params, is_leaf=lambda x: isinstance(x, QuantizedTensor))


def validate_quant_tp(params: Any, specs: Any, tp: int, tp_axis: str) -> None:
    """Fail fast (with the leaf path) when a quantized leaf cannot shard
    under the given PartitionSpec tree: flat-layout leaves cannot shard at
    all; shaped leaves need every tp-sharded dim divisible on codes AND
    absmax (last dim: ``last/2 % tp == 0`` and ``last/block % tp == 0``)."""
    def _uses(p, axis):
        return p == axis or (isinstance(p, (tuple, list)) and axis in p)

    def check(path, leaf, spec):
        if not isinstance(leaf, QuantizedTensor):
            return
        sharded_dims = [i for i in range(len(spec)) if _uses(spec[i], tp_axis)]
        if not sharded_dims:
            return
        if leaf.layout != "shaped":
            raise ValueError(
                f"quantized leaf {path!r} has the flat layout (block "
                f"{leaf.block} does not divide last dim {leaf.shape[-1]}"
                + (", or is odd for nf4's 2-codes/byte packing"
                   if leaf.fmt == "nf4" and leaf.block % 2 else "")
                + f") and cannot shard over {tp_axis!r}; pick a block size "
                "that divides the last dim (--quant_block)"
            )
        for i in sharded_dims:
            if i < leaf.ndim - 1:
                if leaf.shape[i] % tp:
                    raise ValueError(
                        f"quantized leaf {path!r} dim {i} ({leaf.shape[i]}) "
                        f"not divisible by tensor axis {tp}")
            else:
                last = leaf.shape[-1]
                pack = 2 if leaf.fmt == "nf4" else 1
                if (last // pack) % tp or (last // leaf.block) % tp:
                    raise ValueError(
                        f"quantized leaf {path!r} last dim {last} cannot "
                        f"shard {tp}-way: needs last/{pack} and last/block "
                        f"({last}/{leaf.block}={last // leaf.block}) both "
                        f"divisible by {tp}; shrink --quant_block"
                    )

    from distributed_lion_tpu.models.lora import _iter_paths, _tree_get

    for path, leaf in _iter_paths(
            params, ()):
        if isinstance(leaf, QuantizedTensor):
            check("/".join(path), leaf, _tree_get(specs, path))


def dequantize_tree(params: Any, dtype=jnp.float32) -> Any:
    """Dense copy of a tree with quantized leaves (for export/merge-save)."""
    return jax.tree.map(
        lambda w: dequantize(w, dtype) if isinstance(w, QuantizedTensor) else w,
        params,
        is_leaf=lambda x: isinstance(x, QuantizedTensor),
    )
