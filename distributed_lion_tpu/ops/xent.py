"""Chunked-vocab softmax cross entropy: CLM loss without materializing the
full [B, T, V] f32 logits.

At GPT-2 124M flagship shapes the logits tensor is the single largest
activation — microbatch 4 × T 1024 × V 50257 in f32 is ~823 MB, written to
and re-read from HBM around the softmax (and again in backward). Here the
tied-embedding projection, the streaming logsumexp, the label gather, and
the argmax (for the accuracy metric) run per vocab CHUNK inside one
``lax.scan`` whose body is ``jax.checkpoint``-ed: forward keeps only the
running (max, sumexp, label-logit, argmax) carries — peak logits memory
drops to [N, V/chunks] — and backward recomputes each chunk's logits from
(hidden, emb_chunk) instead of loading stored ones.

Exact same math as ``log_softmax`` + gather (pinned to the dense path by
tests/test_xent.py, gradients included); only the schedule differs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def chunked_softmax_xent(
    hidden: jnp.ndarray,
    emb: jnp.ndarray,
    labels: jnp.ndarray,
    n_chunks: int = 8,
    emb_layout: str = "vd",
    valid_v: int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Streaming cross entropy against a tied embedding / LM head.

    Each chunk is carved out of the ORIGINAL head array with a
    ``dynamic_slice`` — no padded or transposed copy of the (possibly
    V=128k × d) head is ever materialized; when V doesn't divide evenly the
    final chunk overlaps the previous one and the already-counted columns
    are masked out of the lse/gather/argmax.

    Args:
        hidden: [N, d] final hidden states (any float dtype; matmul f32-acc).
        emb: the head — [V, d] with ``emb_layout="vd"`` (tied embedding,
            rows are vocab entries) or [d, V] with ``"dv"`` (untied lm_head
            in matmul orientation, e.g. Llama).
        labels: [N] int32 target ids (< V by contract).
        n_chunks: number of vocab chunks.
        valid_v: when > 0, only head columns < ``valid_v`` are real vocab —
            the rest are MXU-alignment padding (models/gpt2
            ``vocab_pad_multiple``) masked out of the lse/gather/argmax
            exactly like tail-chunk overlap columns, so the padded head
            computes the identical loss and its pad rows get zero gradient.

    Returns:
        (nll [N] f32, correct [N] bool) — per-position negative log
        likelihood and argmax-equals-label (for the accuracy metric).
    """
    if emb_layout not in ("vd", "dv"):
        raise ValueError(f"emb_layout must be 'vd' or 'dv', got {emb_layout!r}")
    n, d = hidden.shape
    v = emb.shape[0] if emb_layout == "vd" else emb.shape[1]
    v_real = valid_v if valid_v > 0 else v
    if v_real > v:
        raise ValueError(f"valid_v {v_real} > head columns {v}")
    vc = -(-v // n_chunks)  # ceil; vc <= v always

    @partial(jax.checkpoint, prevent_cse=False)
    def body(carry, cidx):
        m, s, lab, best, besti = carry
        # the tail chunk starts early enough to stay in-bounds; columns it
        # shares with the previous chunk are masked as already-counted
        start = jnp.minimum(cidx * vc, v - vc)
        if emb_layout == "vd":
            ec = lax.dynamic_slice_in_dim(emb, start, vc, axis=0)
            logits = jnp.einsum("nd,vd->nv", hidden, ec.astype(hidden.dtype),
                                preferred_element_type=jnp.float32)
        else:
            ec = lax.dynamic_slice_in_dim(emb, start, vc, axis=1)
            logits = jnp.einsum("nd,dv->nv", hidden, ec.astype(hidden.dtype),
                                preferred_element_type=jnp.float32)
        cols = start + jnp.arange(vc)
        fresh = (cols >= cidx * vc) & (cols < v_real)
        logits = jnp.where(fresh[None, :], logits, -jnp.inf)

        cm = logits.max(-1)
        new_m = jnp.maximum(m, cm)
        # exp(-inf - finite) == 0 handles the all-masked-column case; the
        # m carry starts at -inf so guard its rescale with where:
        scale = jnp.where(jnp.isfinite(m), jnp.exp(m - new_m), 0.0)
        add = jnp.where(jnp.isfinite(cm),
                        jnp.exp(logits - new_m[:, None]).sum(-1), 0.0)
        s = s * scale + add

        local = labels - start
        in_range = (labels >= cidx * vc) & (local < vc)
        gathered = jnp.take_along_axis(
            logits, jnp.clip(local, 0, vc - 1)[:, None], axis=-1
        )[:, 0]
        lab = lab + jnp.where(in_range, gathered, 0.0)

        upd = cm > best
        best = jnp.where(upd, cm, best)
        besti = jnp.where(upd, logits.argmax(-1) + start, besti)
        return (new_m, s, lab, best, besti), None

    init = (
        jnp.full((n,), -jnp.inf, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.full((n,), -jnp.inf, jnp.float32),
        jnp.zeros((n,), jnp.int32),
    )
    (m, s, lab, _, besti), _ = lax.scan(
        body, init, jnp.arange(n_chunks, dtype=jnp.int32)
    )
    lse = m + jnp.log(s)
    nll = lse - lab
    return nll, besti == labels


def tp_vocab_xent(
    hidden: jnp.ndarray,
    head_shard: jnp.ndarray,
    labels: jnp.ndarray,
    axis_name: str,
    valid_v: int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Megatron-style vocab-parallel cross entropy (inside shard_map).

    Each tensor rank holds ``head_shard`` [d, V/tp] — its contiguous slice
    of the lm_head's vocab columns — and computes only those logits: the
    full [N, V] logits never exist on any device, and the head matmul's
    FLOPs split tp ways (the replicated-head TP path computes identical
    full-vocab logits on every rank). The softmax normalizer assembles from
    per-rank (max, sumexp) via ``pmax``/``psum``; the label logit via a
    masked gather on the one rank whose slice contains it; argmax (for the
    accuracy metric) via pmax-then-pmin, matching dense argmax's
    lowest-index tie rule.

    ``hidden`` [N, d] must be replicated over ``axis_name``; it is passed
    through the Megatron copy boundary here, so backward psums d(hidden)
    across ranks — callers get complete backbone gradients without extra
    plumbing. Returns (nll [N] f32, correct [N] bool), identical on every
    rank.

    ``valid_v`` (> 0) marks global columns >= it as MXU-alignment padding
    (models/gpt2 ``vocab_pad_multiple``) and masks them out of the
    normalizer/argmax — shard_map needs the vocab axis to divide evenly, so
    padding is what makes a ragged vocab (GPT-2's 50257) shardable at all;
    the mask keeps the padded math exactly equal to the dense loss.
    """
    from distributed_lion_tpu.parallel.tensor_parallel import (
        copy_to_tp_region,
        reduce_from_tp_region,
    )

    vshard = head_shard.shape[1]
    start = lax.axis_index(axis_name) * vshard
    hidden = copy_to_tp_region(hidden, axis_name)
    logits = jnp.einsum("nd,dv->nv", hidden,
                        head_shard.astype(hidden.dtype),
                        preferred_element_type=jnp.float32)
    if valid_v > 0:
        # pad columns: -inf drops them from the normalizer with zero
        # gradient (m below is a GLOBAL pmax, so even an all-pad rank's
        # exp(-inf - m) underflows cleanly to 0)
        real = (start + jnp.arange(vshard)) < valid_v
        logits = jnp.where(real[None, :], logits, -jnp.inf)
    # the max shift is a constant offset that cancels analytically in the
    # softmax gradient, so detaching it is exact — and the stop_gradient
    # must sit UPSTREAM of the pmax (which defines no differentiation rule)
    # so no tangent ever reaches the collective
    m = lax.pmax(lax.stop_gradient(logits).max(-1), axis_name)
    se = reduce_from_tp_region(jnp.exp(logits - m[:, None]).sum(-1), axis_name)
    lse = jnp.log(se) + m

    in_range = (labels >= start) & (labels < start + vshard)
    idx = jnp.clip(labels - start, 0, vshard - 1)
    lab = jnp.take_along_axis(logits, idx[:, None], axis=-1)[..., 0]
    label_logit = reduce_from_tp_region(jnp.where(in_range, lab, 0.0), axis_name)
    nll = lse - label_logit

    stopped = lax.stop_gradient(logits)  # accuracy metric: no grad path
    # m IS the global max — ranks whose local max reaches it are the argmax
    # candidates; pmin picks the lowest global id (dense argmax's tie rule)
    cand = jnp.where(stopped.max(-1) == m, stopped.argmax(-1) + start,
                     jnp.int32(2**30))
    best_id = lax.pmin(cand, axis_name)
    return nll, best_id == labels


def _shifted_clm_metrics(xent_fn, hidden, tokens, loss_mask):
    """Shared shift-by-one CLM tail: ``xent_fn(h [N,d], labels [N]) ->
    (nll, correct)`` over positions 0..T-2 predicting tokens 1..T-1, masked
    mean loss/accuracy — the one place the contract of
    models/loss.clm_loss_and_metrics is reproduced from hidden states."""
    b, t, d = hidden.shape
    h = hidden[:, :-1].reshape(b * (t - 1), d)
    labels = tokens[:, 1:].reshape(-1).astype(jnp.int32)
    nll, correct = xent_fn(h, labels)
    if loss_mask is None:
        mask = jnp.ones_like(nll)
    else:
        mask = loss_mask[:, 1:].reshape(-1).astype(jnp.float32)
    nmask = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / nmask
    acc = (correct.astype(jnp.float32) * mask).sum() / nmask
    return loss, {"loss": loss, "accuracy": acc, "n_tokens": mask.sum()}


def chunked_clm_loss_seq_parallel(
    hidden: jnp.ndarray,
    emb: jnp.ndarray,
    tokens: jnp.ndarray,
    n_chunks: int,
    axis_name: str,
    emb_layout: str = "vd",
    valid_v: int = 0,
) -> tuple[jnp.ndarray, dict]:
    """Chunked-vocab CE under sequence parallelism (inside shard_map) —
    the composition of :func:`chunked_clm_loss_and_metrics` (no [B, T, V]
    logits materialized) with models/loss.clm_loss_seq_parallel's
    shard-boundary protocol (each device holds a contiguous [B, T_local]
    token chunk; its last position's label arrives from the next shard via
    one [B, 1] ppermute; only the final shard's final position is masked).

    Long-context × huge-vocab is exactly where both tricks matter at once:
    at T=128k sharded 8 ways with a 128k vocab, a single shard's dense
    logits would still be [B, 16k, 128k] f32. Same gradient contract as
    clm_loss_seq_parallel: returns ``local_nll_sum / global_token_count``
    whose seq-axis grad psum (done by the train loop) is the full gradient.
    """
    from distributed_lion_tpu.models.loss import shifted_labels_and_mask

    S = jax.lax.psum(1, axis_name)
    labels, mask = shifted_labels_and_mask(tokens, axis_name)  # [B, T_local]

    nll_sum, correct_sum = masked_local_nll(
        hidden, emb, labels, mask, n_chunks, emb_layout, valid_v)
    n_global = jnp.maximum(jax.lax.psum(mask.sum(), axis_name), 1.0)
    loss_local = nll_sum / n_global
    return loss_local, {
        "loss": jax.lax.psum(loss_local, axis_name),
        "accuracy": jax.lax.psum(correct_sum, axis_name) / n_global,
        "n_tokens": n_global / jnp.maximum(S, 1),
    }


def masked_local_nll(
    hidden: jnp.ndarray,
    head: jnp.ndarray,
    labels: jnp.ndarray,
    mask: jnp.ndarray,
    n_chunks: int = 0,
    emb_layout: str = "vd",
    valid_v: int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """COLLECTIVE-FREE masked NLL partials: ``hidden`` [B, T, d] with
    per-position ``labels``/``mask`` [B, T] → (masked nll sum, masked
    correct sum), both f32 scalars. ``n_chunks > 0`` streams the head
    through :func:`chunked_softmax_xent`; otherwise a dense log_softmax
    (``valid_v`` slices a padded head's columns first).

    Exists for losses that must run inside ``lax.cond`` — the pipelined
    seq-parallel head computes only these local partials on the last stage
    and leaves every psum/ppermute OUTSIDE the cond (XLA aborts on
    collectives under conditional control flow even when all participants
    agree on the branch)."""
    b, t, d = hidden.shape
    flat_labels = labels.reshape(-1).astype(jnp.int32)
    if n_chunks > 0:
        nll, correct = chunked_softmax_xent(
            hidden.reshape(b * t, d), head, flat_labels, n_chunks,
            emb_layout, valid_v)
    else:
        eq = "btd,vd->btv" if emb_layout == "vd" else "btd,dv->btv"
        logits = jnp.einsum(eq, hidden, head.astype(hidden.dtype),
                            preferred_element_type=jnp.float32)
        if valid_v > 0:
            logits = logits[..., :valid_v]
        logp = jax.nn.log_softmax(logits.reshape(b * t, -1), axis=-1)
        nll = -jnp.take_along_axis(logp, flat_labels[:, None], 1)[:, 0]
        correct = logp.argmax(-1) == flat_labels
    fm = mask.reshape(-1).astype(jnp.float32)
    return (nll * fm).sum(), (correct.astype(jnp.float32) * fm).sum()


def tp_vocab_clm_loss_and_metrics(
    hidden: jnp.ndarray,
    head_shard: jnp.ndarray,
    tokens: jnp.ndarray,
    axis_name: str,
    loss_mask: jnp.ndarray | None = None,
    valid_v: int = 0,
) -> tuple[jnp.ndarray, dict]:
    """Shift-by-one CLM loss over a vocab-sharded head — the
    tensor-parallel twin of :func:`chunked_clm_loss_and_metrics`, same
    return contract. ``valid_v`` masks a padded head's alignment columns."""
    return _shifted_clm_metrics(
        lambda h, lab: tp_vocab_xent(h, head_shard, lab, axis_name, valid_v),
        hidden, tokens, loss_mask)


def chunked_clm_loss_and_metrics(
    hidden: jnp.ndarray,
    emb: jnp.ndarray,
    tokens: jnp.ndarray,
    n_chunks: int = 8,
    loss_mask: jnp.ndarray | None = None,
    emb_layout: str = "vd",
    valid_v: int = 0,
) -> tuple[jnp.ndarray, dict]:
    """Shift-by-one CLM loss from FINAL HIDDEN STATES (not logits) — the
    chunked twin of models/loss.clm_loss_and_metrics, same return contract.

    ``hidden`` [B, T, d]; positions 0..T-2 predict tokens 1..T-1. ``emb``
    is the head in either layout (see :func:`chunked_softmax_xent`);
    ``valid_v`` masks MXU-alignment pad columns of a padded head.
    """
    return _shifted_clm_metrics(
        lambda h, lab: chunked_softmax_xent(h, emb, lab, n_chunks, emb_layout,
                                            valid_v),
        hidden, tokens, loss_mask)
