"""Chunked-vocab softmax cross entropy: CLM loss without materializing the
full [B, T, V] f32 logits.

At GPT-2 124M flagship shapes the logits tensor is the single largest
activation — microbatch 4 × T 1024 × V 50257 in f32 is ~823 MB, written to
and re-read from HBM around the softmax (and again in backward). Here the
tied-embedding projection, the streaming logsumexp, the label gather, and
the argmax (for the accuracy metric) run per vocab CHUNK inside one
``lax.scan`` whose body is ``jax.checkpoint``-ed: forward keeps only the
running (max, sumexp, label-logit, argmax) carries — peak logits memory
drops to [N, V/chunks] — and backward recomputes each chunk's logits from
(hidden, emb_chunk) instead of loading stored ones.

Exact same math as ``log_softmax`` + gather (pinned to the dense path by
tests/test_xent.py, gradients included); only the schedule differs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def chunked_softmax_xent(
    hidden: jnp.ndarray,
    emb: jnp.ndarray,
    labels: jnp.ndarray,
    n_chunks: int = 8,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Streaming cross entropy against a tied embedding.

    Args:
        hidden: [N, d] final hidden states (any float dtype; matmul f32-acc).
        emb: [V, d] tied embedding / LM head (rows are vocab entries).
        labels: [N] int32 target ids.
        n_chunks: vocab chunks; V is zero-padded up to a multiple (padded
            rows score -inf-ish via masking, never win argmax or the lse).

    Returns:
        (nll [N] f32, correct [N] bool) — per-position negative log
        likelihood and argmax-equals-label (for the accuracy metric).
    """
    n, d = hidden.shape
    v = emb.shape[0]
    vc = -(-v // n_chunks)
    pad = n_chunks * vc - v
    if pad:
        emb = jnp.concatenate([emb, jnp.zeros((pad, d), emb.dtype)], axis=0)
    emb_chunks = emb.reshape(n_chunks, vc, d)

    @partial(jax.checkpoint, prevent_cse=False)
    def body(carry, inp):
        m, s, lab, best, besti = carry
        ec, cidx = inp
        logits = jnp.einsum("nd,vd->nv", hidden, ec.astype(hidden.dtype),
                            preferred_element_type=jnp.float32)
        # mask zero-pad vocab rows by GLOBAL index (padding can spill across
        # several chunks when vc*n_chunks >> v), so phantom logit-0 columns
        # never enter the lse, the label gather, or the argmax
        col_ok = (cidx * vc + jnp.arange(vc)) < v
        logits = jnp.where(col_ok[None, :], logits, -jnp.inf)

        cm = logits.max(-1)
        new_m = jnp.maximum(m, cm)
        # exp(-inf - finite) == 0 handles the all-masked-column case; the
        # m carry starts at -inf so guard its rescale with where:
        scale = jnp.where(jnp.isfinite(m), jnp.exp(m - new_m), 0.0)
        add = jnp.where(jnp.isfinite(cm),
                        jnp.exp(logits - new_m[:, None]).sum(-1), 0.0)
        s = s * scale + add

        local = labels - cidx * vc
        in_range = (local >= 0) & (local < vc)  # labels < v by contract
        gathered = jnp.take_along_axis(
            logits, jnp.clip(local, 0, vc - 1)[:, None], axis=-1
        )[:, 0]
        lab = lab + jnp.where(in_range, gathered, 0.0)

        upd = cm > best
        best = jnp.where(upd, cm, best)
        besti = jnp.where(upd, logits.argmax(-1) + cidx * vc, besti)
        return (new_m, s, lab, best, besti), None

    init = (
        jnp.full((n,), -jnp.inf, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.full((n,), -jnp.inf, jnp.float32),
        jnp.zeros((n,), jnp.int32),
    )
    (m, s, lab, _, besti), _ = lax.scan(
        body, init, (emb_chunks, jnp.arange(n_chunks, dtype=jnp.int32))
    )
    lse = m + jnp.log(s)
    nll = lse - lab
    return nll, besti == labels


def chunked_clm_loss_and_metrics(
    hidden: jnp.ndarray,
    emb: jnp.ndarray,
    tokens: jnp.ndarray,
    n_chunks: int = 8,
    loss_mask: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, dict]:
    """Shift-by-one CLM loss from FINAL HIDDEN STATES (not logits) — the
    chunked twin of models/loss.clm_loss_and_metrics, same return contract.

    ``hidden`` [B, T, d]; positions 0..T-2 predict tokens 1..T-1.
    """
    b, t, d = hidden.shape
    h = hidden[:, :-1].reshape(b * (t - 1), d)
    labels = tokens[:, 1:].reshape(-1).astype(jnp.int32)
    nll, correct = chunked_softmax_xent(h, emb, labels, n_chunks)
    if loss_mask is None:
        mask = jnp.ones_like(nll)
    else:
        mask = loss_mask[:, 1:].reshape(-1).astype(jnp.float32)
    nmask = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / nmask
    acc = (correct.astype(jnp.float32) * mask).sum() / nmask
    return loss, {"loss": loss, "accuracy": acc, "n_tokens": mask.sum()}
