"""distributed_lion_tpu — a TPU-native framework with the capabilities of
kyleliang919/distributed-lion-pytorch (arXiv:2404.00438).

Brand-new JAX/XLA/Pallas design, not a port:

- ``ops.codec``      — 1-bit sign codec (real uint8 wire format; fixes the
                       reference's accidental int64, distributed_lion.py:75-77).
- ``optim.lion``     — local Lion as a pure optax-style transform
                       (semantics of reference distributed_lion.py:47-59).
- ``optim.distributed_lion`` — majority-vote Distributed Lion: sign votes are
                       psum-reduced on the interconnect (or bit-packed and
                       all-gathered) inside the jit'd update, replacing the
                       reference's per-tensor NCCL all_gather + torch.mode
                       (distributed_lion.py:61-136).
- ``parallel``       — mesh construction, vote collectives, byte accounting,
                       ring attention / sequence parallelism.
- ``models``         — GPT-2- and Llama-class decoders in pure JAX, LoRA.
- ``data``           — fixed-block packing (group_texts), SFT/DPO pipelines.
- ``train``          — jit train loop with NO gradient sync (the reference's
                       AsyncTrainer no_sync contract, async_trainer.py:15),
                       schedules, eval, Orbax checkpointing, metrics.
- ``cli``            — run_clm / run_sft / run_dpo entry points with the
                       reference's ``--lion`` / ``--async_grad`` surface.
"""

from distributed_lion_tpu import compat as _compat  # publishes jax.shard_map on old jax

_compat.install()

__version__ = "0.1.0"
