"""Fixed-block packing: the reference's ``group_texts`` pipeline.

Semantic parity with /root/reference/run_clm.py:509-522: concatenate all
tokenized documents, drop the remainder below a multiple of ``block_size``,
and cut into contiguous blocks (labels == inputs; the shift happens in the
loss). Fixed blocks ⇒ static shapes ⇒ one XLA compilation.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Sequence

import numpy as np


def group_texts(examples: Sequence[Sequence[int]], block_size: int) -> np.ndarray:
    """Concatenate token lists and split into fixed blocks.

    Mirrors run_clm.py:509-522 including the drop-remainder behavior
    ("We drop the small remainder", run_clm.py:513).

    Returns:
        int32 array [n_blocks, block_size].
    """
    concat: List[int] = []
    for ex in examples:
        concat.extend(ex)
    total = (len(concat) // block_size) * block_size
    if total == 0:
        return np.zeros((0, block_size), np.int32)
    return np.asarray(concat[:total], np.int32).reshape(-1, block_size)


def pack_token_stream(
    token_iter: Iterable[Sequence[int]],
    block_size: int,
    buffer_blocks: int = 1024,
) -> Iterator[np.ndarray]:
    """Streaming variant: yields [block_size] blocks from an unbounded
    document iterator with bounded memory (the reference's streaming path,
    run_clm.py:337-352 + ConstantLengthDataset's infinite packing loop,
    sft_llama2.py:122-137)."""
    buf: List[int] = []
    for ex in token_iter:
        buf.extend(ex)
        while len(buf) >= block_size * buffer_blocks:
            chunk = np.asarray(buf[: block_size * buffer_blocks], np.int32)
            del buf[: block_size * buffer_blocks]
            yield from chunk.reshape(-1, block_size)
    while len(buf) >= block_size:
        chunk = np.asarray(buf[:block_size], np.int32)
        del buf[:block_size]
        yield chunk
