from distributed_lion_tpu.data.tokenizer import ByteTokenizer, load_tokenizer
from distributed_lion_tpu.data.packing import group_texts, pack_token_stream
from distributed_lion_tpu.data.sources import (
    synthetic_lm_dataset,
    tokens_from_text_files,
    TokenDataset,
)
