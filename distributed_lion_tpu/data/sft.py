"""SFT data pipeline: prompt formatting, token-ratio estimation, and
constant-length packing.

Capability parity with the reference's SFT data path
(/root/reference/sft_llama2.py):

- :func:`prepare_sample_text` — the "Question:/Answer:" template (:93-96);
- :func:`chars_token_ratio` — estimate chars/token over ~400 samples (:62-75);
- :func:`constant_length_batches` — TRL ConstantLengthDataset semantics
  (:122-137): fill a char-budget buffer, tokenize, append EOS, concatenate,
  cut fixed seq_length blocks, loop infinitely;
- :func:`load_pairs_jsonl` — zero-egress stand-in for streaming
  ``lvwerra/stack-exchange-paired`` (:99-121): local JSONL with
  question/response fields, take/skip train-eval split.
"""

from __future__ import annotations

import json
import pathlib
from typing import Iterable, Iterator, List, Sequence

import numpy as np

from distributed_lion_tpu.data.packing import pack_token_stream


def prepare_sample_text(example: dict) -> str:
    """sft_llama2.py:93-96 verbatim template."""
    return f"Question: {example['question']}\n\nAnswer: {example['response_j']}"


def chars_token_ratio(samples: Sequence[dict], tokenizer, nb_examples: int = 400) -> float:
    """sft_llama2.py:62-75: total chars / total tokens over the first
    ``nb_examples`` samples."""
    total_chars, total_tokens = 0, 0
    for example in list(samples)[:nb_examples]:
        text = prepare_sample_text(example)
        total_chars += len(text)
        total_tokens += len(tokenizer.encode(text))
    return total_chars / max(total_tokens, 1)


def load_pairs_jsonl(path: str | pathlib.Path, *, size_valid_set: int = 0) -> tuple:
    """Load {"question", "response_j", ...} records; split off the first
    ``size_valid_set`` as validation (the reference's streaming
    take/skip split, sft_llama2.py:104-117)."""
    records: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    valid = records[:size_valid_set]
    train = records[size_valid_set:]
    return train, valid


def synthetic_qa_pairs(n: int, seed: int = 0) -> List[dict]:
    """Learnable synthetic Q/A corpus for tests and offline smoke runs."""
    rng = np.random.default_rng(seed)
    ops = [("plus", lambda a, b: a + b), ("times", lambda a, b: a * b)]
    out = []
    for _ in range(n):
        a, b = int(rng.integers(0, 50)), int(rng.integers(0, 50))
        name, fn = ops[int(rng.integers(0, len(ops)))]
        out.append({
            "question": f"What is {a} {name} {b}?",
            "response_j": f"The answer is {fn(a, b)}.",
            "response_k": f"The answer is {fn(a, b) + int(rng.integers(1, 7))}.",
        })
    return out


def padded_examples(
    samples: Sequence[dict],
    tokenizer,
    seq_length: int,
    *,
    format_fn=prepare_sample_text,
    group_by_length: bool = False,
) -> tuple:
    """Non-packed SFT rows: one example per row, truncated/EOS-terminated/
    padded to ``seq_length`` — the reference base-trainer's alternative to
    ConstantLengthDataset packing (sft_llama2.py:53-54 implies it via the
    packing×group_by_length exclusivity guard). Returns
    ``(tokens [n, seq] int32, mask [n, seq] f32)`` with the mask covering
    real tokens only, so padding never contributes loss.

    ``group_by_length`` sorts rows by true token length (HF Trainer's
    ``group_by_length``: neighbors in a batch have similar lengths →
    minimal padding waste)."""
    eos = getattr(tokenizer, "eos_id", 0)
    pad = getattr(tokenizer, "pad_id", eos)
    rows = []
    for s in samples:
        ids = tokenizer.encode(format_fn(s)) + [eos]
        rows.append(ids[:seq_length])
    if not rows:
        raise ValueError("no SFT samples")
    if group_by_length:
        rows.sort(key=len)
    tokens = np.full((len(rows), seq_length), pad, np.int32)
    mask = np.zeros((len(rows), seq_length), np.float32)
    for i, ids in enumerate(rows):
        tokens[i, : len(ids)] = ids
        mask[i, : len(ids)] = 1.0
    return tokens, mask


def padded_batch_iterator(
    tokens: np.ndarray,
    mask: np.ndarray,
    global_batch: int,
    *,
    seed: int = 0,
    shuffle: bool = True,
    length_grouped: bool = False,
) -> Iterator[dict]:
    """Cycle {"tokens", "mask"} batches forever, reshuffled per epoch.

    ``length_grouped=False`` permutes EXAMPLES each epoch (HF RandomSampler:
    fresh batch composition every epoch); ``length_grouped=True`` keeps rows
    in their length-sorted order and permutes whole BATCHES (HF's
    LengthGroupedSampler: neighbors stay similar-length, padding waste stays
    minimal)."""
    n = len(tokens)
    if n < global_batch:
        raise ValueError(f"{n} examples < global batch {global_batch}")
    rng = np.random.default_rng(seed)
    n_batches = n // global_batch
    while True:
        if length_grouped:
            # per-epoch random offset slides the drop-last residue window, so
            # with n % global_batch != 0 the longest rows are not permanently
            # excluded (HF's LengthGroupedSampler re-forms groups per epoch)
            resid = n - n_batches * global_batch
            off = int(rng.integers(0, resid + 1)) if (shuffle and resid) else 0
            starts = (rng.permutation(n_batches) if shuffle
                      else np.arange(n_batches)) * global_batch + off
            idx_batches = [np.arange(s, s + global_batch) for s in starts]
        else:
            order = rng.permutation(n) if shuffle else np.arange(n)
            idx_batches = [order[i * global_batch : (i + 1) * global_batch]
                           for i in range(n_batches)]
        for idx in idx_batches:
            yield {
                "tokens": np.ascontiguousarray(tokens[idx]),
                "mask": np.ascontiguousarray(mask[idx]),
            }


def constant_length_batches(
    samples: Iterable[dict],
    tokenizer,
    seq_length: int = 1024,
    *,
    infinite: bool = True,
    format_fn=prepare_sample_text,
    chars_per_token: float = 3.6,
    num_sequences_buffer: int = 1024,
) -> Iterator[np.ndarray]:
    """Yield [seq_length] int32 sequences, TRL ConstantLengthDataset-style:
    format + tokenize each sample, EOS-join, concatenate, cut fixed blocks;
    when ``infinite``, restart the sample iterator forever (sft_llama2.py's
    infinite packing loop, :122-137).

    Built on :func:`~distributed_lion_tpu.data.packing.pack_token_stream`, so
    finite mode drains every sample. ``chars_per_token`` is accepted for API
    parity with the reference (which uses it to size a char-budget buffer,
    :130); tokenizing lazily makes the heuristic unnecessary here.
    """
    del chars_per_token
    samples = list(samples)
    if not samples:
        raise ValueError("no SFT samples")
    eos = getattr(tokenizer, "eos_id", 0)

    def token_iter():
        while True:
            for s in samples:
                yield tokenizer.encode(format_fn(s)) + [eos]
            if not infinite:
                return

    yield from pack_token_stream(token_iter(), seq_length, buffer_blocks=num_sequences_buffer)
