"""Tokenizers.

The reference borrows GPT-2's BPE / Llama's SP tokenizer from HF hub
(/root/reference/run_clm.py:398-423). This environment is zero-egress, so:

- :func:`load_tokenizer` uses a locally cached HF tokenizer when one exists
  (``transformers`` is baked in; hub download is attempted only if a cache
  is present);
- :class:`ByteTokenizer` is the dependency-free fallback: 256 byte ids +
  BOS/EOS/PAD, enough for real training runs on local text and for all
  tests/benchmarks. Token-id space is model-config-driven either way.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, List


@dataclass(frozen=True)
class ByteTokenizer:
    """UTF-8 byte-level tokenizer: ids 0..255 are bytes, then specials."""

    bos_id: int = 256
    eos_id: int = 257
    pad_id: int = 258

    @property
    def vocab_size(self) -> int:
        return 259

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> List[int]:
        ids = list(text.encode("utf-8"))
        if add_bos:
            ids = [self.bos_id] + ids
        if add_eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


def load_tokenizer(name_or_path: str | None):
    """Resolve a tokenizer, zero-egress:

    - ``bpe:<dir>`` or a directory containing ``vocab.json`` + ``merges.txt``
      → the native GPT-2 byte-level BPE (data.bpe — drop the real GPT-2
      files in and get the real 50257 vocab);
    - ``sp:<path>``, a ``*.model`` file, or a directory containing
      ``tokenizer.model`` → the native SentencePiece BPE reader (data.spm)
      — a local Llama-2/Mistral checkpoint gets its true 32000 vocab;
    - a ``tokenizer.json`` file or a directory containing one → the native
      HF fast-tokenizer BPE reader (data.hf_tokenizer_json) — Llama-3's
      128256 vocab, GPT-2's 50257;
    - otherwise a locally cached HF tokenizer when one exists;
    - :class:`ByteTokenizer` as the dependency-free fallback — with a LOUD
      warning when ``name_or_path`` was set but unresolvable, because
      silently training a "Llama" run on the 259-id byte vocab is the
      classic footgun.
    """
    import os

    if name_or_path:
        from distributed_lion_tpu.data.bpe import BPETokenizer

        if name_or_path.startswith("bpe:"):
            return BPETokenizer.load(name_or_path[len("bpe:"):])
        if name_or_path.startswith("sp:"):
            from distributed_lion_tpu.data.spm import SentencePieceTokenizer

            return SentencePieceTokenizer.load(name_or_path[len("sp:"):])
        if (os.path.isdir(name_or_path)
                and os.path.exists(os.path.join(name_or_path, "vocab.json"))
                and os.path.exists(os.path.join(name_or_path, "merges.txt"))):
            return BPETokenizer.load(name_or_path)
        if (name_or_path.endswith(".model") and os.path.isfile(name_or_path)
                ) or (os.path.isdir(name_or_path) and os.path.exists(
                    os.path.join(name_or_path, "tokenizer.model"))):
            from distributed_lion_tpu.data.spm import SentencePieceTokenizer

            return SentencePieceTokenizer.load(name_or_path)
        if (name_or_path.endswith("tokenizer.json")
                and os.path.isfile(name_or_path)
                ) or (os.path.isdir(name_or_path) and os.path.exists(
                    os.path.join(name_or_path, "tokenizer.json"))):
            from distributed_lion_tpu.data.hf_tokenizer_json import (
                TokenizerJSON)

            return TokenizerJSON.load(name_or_path)
        try:
            from transformers import AutoTokenizer

            tok = AutoTokenizer.from_pretrained(name_or_path, local_files_only=True)

            class _HFAdapter:
                vocab_size = int(tok.vocab_size)
                eos_id = tok.eos_token_id if tok.eos_token_id is not None else 0
                bos_id = tok.bos_token_id if tok.bos_token_id is not None else eos_id
                pad_id = tok.pad_token_id if tok.pad_token_id is not None else eos_id

                @staticmethod
                def encode(text, add_bos=False, add_eos=False):
                    ids = tok.encode(text, add_special_tokens=False)
                    if add_bos:
                        ids = [_HFAdapter.bos_id] + ids
                    if add_eos:
                        ids = ids + [_HFAdapter.eos_id]
                    return ids

                @staticmethod
                def decode(ids):
                    return tok.decode(list(ids))

            return _HFAdapter()
        except Exception:  # graft: disable=DLT006
            pass  # deliberate fallback chain: no `tokenizers` wheel / no
            # tokenizer.json here is an expected miss, and the loud WARNING
            # below names every path that was tried
        from distributed_lion_tpu.train.journal import emit

        emit(
            f"[tokenizer] WARNING: could not resolve {name_or_path!r} to a "
            "real tokenizer (no vocab.json+merges.txt, tokenizer.model, "
            "tokenizer.json, or local HF cache) — falling back to the "
            "259-id ByteTokenizer. A Llama/GPT-2 run with this vocab is "
            "almost certainly not what you want.",
            stderr=True,
        )
    return ByteTokenizer()
