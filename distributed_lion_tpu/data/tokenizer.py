"""Tokenizers.

The reference borrows GPT-2's BPE / Llama's SP tokenizer from HF hub
(/root/reference/run_clm.py:398-423). This environment is zero-egress, so:

- :func:`load_tokenizer` uses a locally cached HF tokenizer when one exists
  (``transformers`` is baked in; hub download is attempted only if a cache
  is present);
- :class:`ByteTokenizer` is the dependency-free fallback: 256 byte ids +
  BOS/EOS/PAD, enough for real training runs on local text and for all
  tests/benchmarks. Token-id space is model-config-driven either way.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, List


@dataclass(frozen=True)
class ByteTokenizer:
    """UTF-8 byte-level tokenizer: ids 0..255 are bytes, then specials."""

    bos_id: int = 256
    eos_id: int = 257
    pad_id: int = 258

    @property
    def vocab_size(self) -> int:
        return 259

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> List[int]:
        ids = list(text.encode("utf-8"))
        if add_bos:
            ids = [self.bos_id] + ids
        if add_eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


def load_tokenizer(name_or_path: str | None):
    """Resolve a tokenizer, zero-egress:

    - ``bpe:<dir>`` or a directory containing ``vocab.json`` + ``merges.txt``
      → the native GPT-2 byte-level BPE (data.bpe — drop the real GPT-2
      files in and get the real 50257 vocab);
    - otherwise a locally cached HF tokenizer when one exists;
    - :class:`ByteTokenizer` as the dependency-free fallback.
    """
    import os

    if name_or_path:
        from distributed_lion_tpu.data.bpe import BPETokenizer

        if name_or_path.startswith("bpe:"):
            return BPETokenizer.load(name_or_path[len("bpe:"):])
        if (os.path.isdir(name_or_path)
                and os.path.exists(os.path.join(name_or_path, "vocab.json"))
                and os.path.exists(os.path.join(name_or_path, "merges.txt"))):
            return BPETokenizer.load(name_or_path)
        try:
            from transformers import AutoTokenizer

            tok = AutoTokenizer.from_pretrained(name_or_path, local_files_only=True)

            class _HFAdapter:
                vocab_size = int(tok.vocab_size)
                eos_id = tok.eos_token_id if tok.eos_token_id is not None else 0
                bos_id = tok.bos_token_id if tok.bos_token_id is not None else eos_id
                pad_id = tok.pad_token_id if tok.pad_token_id is not None else eos_id

                @staticmethod
                def encode(text, add_bos=False, add_eos=False):
                    ids = tok.encode(text, add_special_tokens=False)
                    if add_bos:
                        ids = [_HFAdapter.bos_id] + ids
                    if add_eos:
                        ids = ids + [_HFAdapter.eos_id]
                    return ids

                @staticmethod
                def decode(ids):
                    return tok.decode(list(ids))

            return _HFAdapter()
        except Exception:
            pass
    return ByteTokenizer()
