"""GPT-2 byte-level BPE: the exact algorithm and file format, offline.

The reference tokenizes with GPT-2's BPE pulled from HF hub
(/root/reference/run_clm.py:398-423). This environment is zero-egress, so the
tokenizer itself is implemented here — the same byte↔unicode table,
pre-tokenization regex, and merge procedure GPT-2 published — reading the
standard ``vocab.json`` + ``merges.txt`` files:

- drop in the real GPT-2 files (from any HF checkout) and ``encode`` matches
  ``GPT2Tokenizer`` token-for-token (pinned by tests/test_bpe.py against
  ``transformers``' implementation on locally-trained files);
- or learn a corpus-specific vocabulary with :func:`train_bpe`
  (``cli.train_bpe``) — same format, loadable by HF tooling too.

No network, no transformers dependency at runtime.
"""

from __future__ import annotations

import json
import os
from functools import lru_cache
from typing import Iterable, List, Optional

import numpy as np

try:  # \p{L}/\p{N} classes need the `regex` module (baked in)
    import regex as _re
except ImportError:  # pragma: no cover
    _re = None

# GPT-2's pre-tokenization pattern (contractions, letter runs, number runs,
# punctuation runs, whitespace) — the published pattern, verbatim.
_PAT = (r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+|"""
        r""" ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+""")

END_OF_TEXT = "<|endoftext|>"


@lru_cache(maxsize=1)
def bytes_to_unicode() -> dict:
    """GPT-2's reversible byte → printable-unicode map: the 188 'visible'
    bytes map to themselves; the rest shift up by 256. Keeps merges.txt
    printable while covering all 256 byte values."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("¡"), ord("¬") + 1))
          + list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, (chr(c) for c in cs)))


@lru_cache(maxsize=1)
def unicode_to_bytes() -> dict:
    return {v: k for k, v in bytes_to_unicode().items()}


def _get_pairs(word: tuple) -> set:
    return {(word[i], word[i + 1]) for i in range(len(word) - 1)}


class _NativeCore:
    """ctypes bridge to the C++ merge core (native/bpe_core.cc).

    Lowers the tokenizer's tables into id space once — vocab as raw
    byte-strings indexed by id, merges as (left_id, right_id) pairs in rank
    order — then encodes whole documents with one C call over the
    regex-pre-tokenized byte stream. Output is pinned token-for-token to the
    Python ``_bpe`` path by tests/test_bpe.py."""

    def __init__(self, vocab: dict, ranks: dict):
        import ctypes

        from distributed_lion_tpu import native

        self._lib = native.load_bpe()
        n = 1 + max(vocab.values(), default=-1)
        if n > 4 * max(len(vocab), 1):
            raise ValueError("native BPE: vocab id space too sparse")
        by_id: List[Optional[str]] = [None] * n
        for t, i in vocab.items():
            if not (0 <= i < n) or by_id[i] is not None:
                raise ValueError("native BPE needs unique, non-negative "
                                 "vocab ids")
            by_id[i] = t
        u2b = unicode_to_bytes()

        def raw(tok: Optional[str]) -> bytes:
            if tok is None:  # id-space hole (e.g. tokenizer.json vocab
                return b""   # with a gap before added tokens): unreachable
            try:
                return bytes(u2b[c] for c in tok)
            except KeyError:  # specials outside the b2u alphabet
                return tok.encode("utf-8")

        blobs = [raw(t) for t in by_id]
        blob = b"".join(blobs)
        off = np.zeros(n + 1, np.int64)
        np.cumsum([len(b) for b in blobs], out=off[1:])
        ordered = sorted(ranks.items(), key=lambda kv: kv[1])
        pairs = np.asarray(
            [[vocab[a], vocab[b]] for (a, b), _ in ordered], np.int32
        ).reshape(-1)
        self._blob = np.frombuffer(blob, np.uint8).copy()
        c_u8p = ctypes.POINTER(ctypes.c_uint8)
        c_i64p = ctypes.POINTER(ctypes.c_int64)
        c_i32p = ctypes.POINTER(ctypes.c_int32)
        self._c = (c_u8p, c_i64p, c_i32p)
        handle = self._lib.bpe_new(
            self._blob.ctypes.data_as(c_u8p), off.ctypes.data_as(c_i64p),
            n, pairs.ctypes.data_as(c_i32p) if pairs.size else
            np.zeros(1, np.int32).ctypes.data_as(c_i32p), len(ordered),
        )
        if not handle:
            raise RuntimeError(
                f"bpe_new failed: {self._lib.bpe_last_error().decode()}"
            )
        self._h = handle

    def encode_pretoks(self, pretoks: List[bytes]) -> np.ndarray:
        """[pre-token byte strings] → int32 ids (one C call)."""
        c_u8p, c_i64p, c_i32p = self._c
        blob = b"".join(pretoks)
        buf = np.frombuffer(blob, np.uint8)
        off = np.zeros(len(pretoks) + 1, np.int64)
        np.cumsum([len(p) for p in pretoks], out=off[1:])
        cap = len(blob) + 8  # merges only shrink the per-byte id sequence
        out = np.empty(cap, np.int32)
        k = self._lib.bpe_encode(
            self._h,
            buf.ctypes.data_as(c_u8p) if buf.size else
            np.zeros(1, np.uint8).ctypes.data_as(c_u8p),
            off.ctypes.data_as(c_i64p), len(pretoks),
            out.ctypes.data_as(c_i32p), cap,
        )
        if k < 0:  # can't happen with cap >= len(blob); defensive retry
            out = np.empty(-k, np.int32)
            k = self._lib.bpe_encode(
                self._h, buf.ctypes.data_as(c_u8p),
                off.ctypes.data_as(c_i64p), len(pretoks),
                out.ctypes.data_as(c_i32p), -k,
            )
        return out[:k]

    def __del__(self):  # pragma: no cover
        try:
            self._lib.bpe_free(self._h)
        except Exception:
            pass


class BPETokenizer:
    """Byte-level BPE over a ``vocab.json`` (token → id) + ranked
    ``merges.txt``. API-compatible with data.tokenizer.ByteTokenizer."""

    def __init__(self, vocab: dict, merges: List[tuple],
                 specials: Optional[List[str]] = None):
        if _re is None:
            raise RuntimeError("the `regex` module is required for GPT-2 BPE")
        self.vocab = dict(vocab)
        self.ranks = {tuple(m): i for i, m in enumerate(merges)}
        # specials=None → the GPT-2 default; an explicit [] means "none"
        # (the tokenizer.json reader manages added tokens itself)
        specials = [END_OF_TEXT] if specials is None else specials
        for s in specials:
            if s not in self.vocab:
                self.vocab[s] = len(self.vocab)
        self._special_ids = {self.vocab[s] for s in specials
                             if s in self.vocab}
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        self._pat = _re.compile(_PAT)
        self._cache: dict = {}
        self._native: object = None  # _NativeCore, False (disabled), or None
        self.eos_id = self.vocab.get(END_OF_TEXT, len(self.vocab) - 1)
        self.bos_id = self.eos_id  # GPT-2 convention: <|endoftext|> is both
        self.pad_id = self.eos_id

    def _native_core(self) -> Optional["_NativeCore"]:
        """Lazily build the C++ merge core; any failure (no compiler,
        non-dense ids) pins this tokenizer to the Python path."""
        if self._native is None:
            if os.environ.get("DLION_NATIVE_BPE", "1") == "0":
                self._native = False
            else:
                try:
                    self._native = _NativeCore(self.vocab, self.ranks)
                except Exception:
                    self._native = False
        return self._native or None

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    # ------------------------------------------------------------------ codec
    def _bpe(self, token: str) -> List[str]:
        if token in self._cache:
            return self._cache[token]
        word = tuple(token)
        while len(word) > 1:
            pairs = _get_pairs(word)
            best = min(pairs, key=lambda p: self.ranks.get(p, float("inf")))
            if best not in self.ranks:
                break
            first, second = best
            out: List[str] = []
            i = 0
            while i < len(word):
                if (i < len(word) - 1 and word[i] == first
                        and word[i + 1] == second):
                    out.append(first + second)
                    i += 2
                else:
                    out.append(word[i])
                    i += 1
            word = tuple(out)
        result = list(word)
        if len(self._cache) < 65536:
            self._cache[token] = result
        return result

    def encode(self, text: str, add_bos: bool = False,
               add_eos: bool = False) -> List[int]:
        core = self._native_core()
        if core is not None:
            # native path: regex pre-tokenize here, merge in C++ (raw bytes;
            # the byte→unicode mapping lives in the lowered id tables)
            pretoks = [t.encode("utf-8") for t in self._pat.findall(text)]
            body = core.encode_pretoks(pretoks).tolist() if pretoks else []
            return ([self.bos_id] if add_bos else []) + body + (
                [self.eos_id] if add_eos else [])
        b2u = bytes_to_unicode()
        ids: List[int] = []
        if add_bos:
            ids.append(self.bos_id)
        for tok in self._pat.findall(text):
            mapped = "".join(b2u[b] for b in tok.encode("utf-8"))
            for piece in self._bpe(mapped):
                ids.append(self.vocab[piece])
        if add_eos:
            ids.append(self.eos_id)
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        u2b = unicode_to_bytes()
        text = "".join(self.inv_vocab[int(i)] for i in ids
                       if int(i) in self.inv_vocab
                       and int(i) not in self._special_ids)
        data = bytes(u2b[c] for c in text if c in u2b)
        return data.decode("utf-8", errors="replace")

    # --------------------------------------------------------------------- io
    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        """Load HF-format ``vocab.json`` + ``merges.txt`` from a directory
        (the files ``GPT2Tokenizer`` ships/consumes)."""
        with open(os.path.join(path, "vocab.json"), encoding="utf-8") as f:
            vocab = json.load(f)
        merges = []
        with open(os.path.join(path, "merges.txt"), encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line or line.startswith("#version"):
                    continue
                a, b = line.split(" ")
                merges.append((a, b))
        return cls(vocab, merges)

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "vocab.json"), "w", encoding="utf-8") as f:
            json.dump(self.vocab, f, ensure_ascii=False, allow_nan=False)
        ordered = sorted(self.ranks.items(), key=lambda kv: kv[1])
        with open(os.path.join(path, "merges.txt"), "w", encoding="utf-8") as f:
            f.write("#version: 0.2\n")
            for (a, b), _ in ordered:
                f.write(f"{a} {b}\n")


def train_bpe(texts: Iterable[str], vocab_size: int,
              specials: Optional[List[str]] = None) -> BPETokenizer:
    """Learn a byte-level BPE vocabulary (GPT-2 procedure): start from the
    256 byte symbols, repeatedly merge the most frequent adjacent pair
    within pre-tokenized words until ``vocab_size`` (minus specials) is
    reached. Same format as GPT-2's published tokenizer — the real
    vocab/merges files are a drop-in replacement."""
    if _re is None:
        raise RuntimeError("the `regex` module is required for BPE training")
    pat = _re.compile(_PAT)
    b2u = bytes_to_unicode()

    # word frequency table over pre-tokens (mapped to the unicode alphabet)
    word_freq: dict = {}
    for text in texts:
        for tok in pat.findall(text):
            mapped = tuple(b2u[b] for b in tok.encode("utf-8"))
            if mapped:
                word_freq[mapped] = word_freq.get(mapped, 0) + 1

    vocab = {ch: i for i, ch in enumerate(sorted(bytes_to_unicode().values()))}
    specials = list(specials or [END_OF_TEXT])
    target_merges = max(0, vocab_size - len(vocab) - len(specials))
    merges: List[tuple] = []

    words = list(word_freq.items())
    for _ in range(target_merges):
        pair_freq: dict = {}
        for word, freq in words:
            for i in range(len(word) - 1):
                p = (word[i], word[i + 1])
                pair_freq[p] = pair_freq.get(p, 0) + freq
        if not pair_freq:
            break
        best = max(pair_freq.items(), key=lambda kv: (kv[1], kv[0]))[0]
        if pair_freq[best] < 2:
            break
        merges.append(best)
        merged = best[0] + best[1]
        vocab[merged] = len(vocab)
        new_words = []
        for word, freq in words:
            if len(word) > 1:
                out = []
                i = 0
                while i < len(word):
                    if (i < len(word) - 1 and word[i] == best[0]
                            and word[i + 1] == best[1]):
                        out.append(merged)
                        i += 2
                    else:
                        out.append(word[i])
                        i += 1
                word = tuple(out)
            new_words.append((word, freq))
        words = new_words
    return BPETokenizer(vocab, merges, specials)
