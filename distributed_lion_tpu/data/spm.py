"""Native SentencePiece ``tokenizer.model`` reader + BPE encoder (offline).

The reference tokenizes its SFT/DPO workloads with Llama's SentencePiece
tokenizer pulled from HF hub (/root/reference/sft_llama2.py:157-158,
dpo_llama2.py:129-131). This environment is zero-egress and the
``sentencepiece`` wheel is not installed, so this module reads the
serialized ``ModelProto`` directly (a ~60-line protobuf wire-format walker —
the format is stable and public) and implements the SentencePiece *BPE*
encoding algorithm natively:

- whitespace is escaped to ``▁`` (U+2581) and a dummy prefix ``▁`` is
  prepended when the model's ``NormalizerSpec.add_dummy_prefix`` says so
  (Llama-2's does);
- adjacent symbols are greedily merged by *piece score* (highest first,
  leftmost on ties) while the concatenation exists in the vocab — the
  linked-list + heap scheme, so encoding is O(n log n) over whole documents
  (SentencePiece does not pre-tokenize);
- characters that never reach a vocab piece fall back to the ``<0xXX>``
  byte pieces when the model has them (Llama-2's ``byte_fallback``), else
  to ``unk_id``;
- CONTROL/UNKNOWN pieces (``<s>``, ``</s>``, ``<unk>``) are never produced
  from raw text; USER_DEFINED pieces are matched greedily before BPE, the
  way SentencePiece treats them.

Llama-2's 32000-vocab model is exactly this shape, so a local checkpoint
directory containing ``tokenizer.model`` tokenizes with its true vocabulary
and no ``transformers``/HF-cache dependency.
"""

from __future__ import annotations

import heapq
import os
import struct
from typing import Iterable, List, Optional, Tuple

_SPACE = "▁"  # '▁'

# SentencePiece.Type enum (sentencepiece_model.proto)
_NORMAL, _UNKNOWN, _CONTROL, _USER_DEFINED, _UNUSED, _BYTE = 1, 2, 3, 4, 5, 6


# --------------------------------------------------------- protobuf wire walk

def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) — value is int for varint,
    bytes for length-delimited, raw 4/8 bytes for fixed."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        else:  # groups (3/4) don't occur in sentencepiece_model.proto
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield field, wt, v


def _parse_piece(buf: bytes) -> Tuple[str, float, int]:
    piece, score, ptype = "", 0.0, _NORMAL
    for field, wt, v in _fields(buf):
        if field == 1 and wt == 2:
            piece = v.decode("utf-8")
        elif field == 2 and wt == 5:
            score = struct.unpack("<f", v)[0]
        elif field == 3 and wt == 0:
            ptype = v
    return piece, score, ptype


def parse_model_proto(data: bytes) -> dict:
    """Serialized ``ModelProto`` → {pieces: [(piece, score, type)],
    model_type, add_dummy_prefix, unk/bos/eos/pad ids}."""
    pieces: List[Tuple[str, float, int]] = []
    out = {
        "model_type": 1,  # UNIGRAM default
        "add_dummy_prefix": True,
        "unk_id": 0, "bos_id": 1, "eos_id": 2, "pad_id": -1,
    }
    for field, wt, v in _fields(data):
        if field == 1 and wt == 2:  # repeated SentencePiece pieces
            pieces.append(_parse_piece(v))
        elif field == 2 and wt == 2:  # TrainerSpec
            for f2, wt2, v2 in _fields(v):
                if wt2 != 0:
                    continue
                # int32 negatives arrive 64-bit sign-extended; any of the
                # special-token ids may be -1 (= disabled) in a valid model
                v2s = v2 - (1 << 64) if v2 >= 1 << 63 else v2
                if f2 == 3:
                    out["model_type"] = v2s  # 1=unigram 2=bpe
                elif f2 == 40:
                    out["unk_id"] = v2s
                elif f2 == 41:
                    out["bos_id"] = v2s
                elif f2 == 42:
                    out["eos_id"] = v2s
                elif f2 == 43:
                    out["pad_id"] = v2s
        elif field == 3 and wt == 2:  # NormalizerSpec
            for f3, wt3, v3 in _fields(v):
                if f3 == 3 and wt3 == 0:
                    out["add_dummy_prefix"] = bool(v3)
    out["pieces"] = pieces
    return out


# ------------------------------------------------------------------ tokenizer

class SentencePieceTokenizer:
    """SentencePiece BPE over a serialized ``tokenizer.model``.

    API-compatible with data.tokenizer.ByteTokenizer (vocab_size,
    bos/eos/pad ids, encode/decode). Only BPE-type models are supported —
    Llama/Mistral ship BPE; a unigram model raises loudly rather than
    tokenizing wrong.
    """

    def __init__(self, proto: dict):
        if proto["model_type"] != 2:
            raise ValueError(
                "only SentencePiece BPE models are supported (this model is "
                f"type {proto['model_type']}; Llama's tokenizer.model is BPE)"
            )
        self.pieces = proto["pieces"]
        self.id_to_piece = [p for p, _, _ in self.pieces]
        self.piece_type = [t for _, _, t in self.pieces]
        # mergeable lookup: raw-text-reachable pieces only
        self._scores = {
            p: (s, i) for i, (p, s, t) in enumerate(self.pieces)
            if t in (_NORMAL, _USER_DEFINED)
        }
        self._byte_id = {}
        for i, (p, _, t) in enumerate(self.pieces):
            if t == _BYTE:  # '<0xXX>'
                self._byte_id[int(p[3:5], 16)] = i
        self._user_defined = sorted(
            (p for p, _, t in self.pieces if t == _USER_DEFINED),
            key=len, reverse=True,
        )
        self.add_dummy_prefix = proto["add_dummy_prefix"]
        self.unk_id = proto["unk_id"]
        self.bos_id = proto["bos_id"]
        self.eos_id = proto["eos_id"]
        self.pad_id = (proto["pad_id"] if proto["pad_id"] >= 0
                       else max(proto["eos_id"], 0))

    @classmethod
    def load(cls, path: str) -> "SentencePieceTokenizer":
        """``path``: a ``tokenizer.model`` file or a directory holding one."""
        if os.path.isdir(path):
            path = os.path.join(path, "tokenizer.model")
        with open(path, "rb") as f:
            return cls(parse_model_proto(f.read()))

    @property
    def vocab_size(self) -> int:
        return len(self.pieces)

    # ------------------------------------------------------------------ encode
    def _merge(self, chars: List[str]) -> List[str]:
        """Greedy highest-score adjacent merge (leftmost on ties) — the
        SentencePiece BPE procedure, via linked list + lazy heap."""
        n = len(chars)
        if n < 2:
            return chars
        sym = list(chars)
        left = list(range(-1, n - 1))
        right = list(range(1, n + 1))
        alive = [True] * n
        rev = [0] * n
        heap: list = []

        def push(a: int, b: int) -> None:
            cand = sym[a] + sym[b]
            sc = self._scores.get(cand)
            if sc is not None:
                heapq.heappush(heap, (-sc[0], a, rev[a], rev[b], b))

        for i in range(n - 1):
            push(i, i + 1)
        while heap:
            _, a, ra, rb, b = heapq.heappop(heap)
            if not (alive[a] and alive[b]) or rev[a] != ra or rev[b] != rb:
                continue
            sym[a] += sym[b]
            rev[a] += 1
            alive[b] = False
            right[a] = right[b]
            if right[b] < n:
                left[right[b]] = a
            if left[a] >= 0:
                push(left[a], a)
            if right[a] < n:
                push(a, right[a])
        return [sym[i] for i in range(n) if alive[i]]

    def _piece_ids(self, piece: str, out: List[int]) -> None:
        sc = self._scores.get(piece)
        if sc is not None:
            out.append(sc[1])
        elif self._byte_id:
            for byte in piece.encode("utf-8"):
                out.append(self._byte_id.get(byte, self.unk_id))
        else:
            out.append(self.unk_id)

    def encode(self, text: str, add_bos: bool = False,
               add_eos: bool = False) -> List[int]:
        norm = text.replace(" ", _SPACE)
        if self.add_dummy_prefix and norm and not norm.startswith(_SPACE):
            norm = _SPACE + norm
        # a negative id means the model disables that special token
        ids: List[int] = [self.bos_id] if add_bos and self.bos_id >= 0 else []
        for chunk, literal in self._split_user_defined(norm):
            if literal:
                ids.append(self._scores[chunk][1])
            else:
                for piece in self._merge(list(chunk)):
                    self._piece_ids(piece, ids)
        if add_eos and self.eos_id >= 0:
            ids.append(self.eos_id)
        return ids

    def _split_user_defined(self, text: str):
        """Yield (chunk, is_literal): USER_DEFINED pieces match greedily
        before BPE, the rest is merged normally."""
        if not self._user_defined:
            yield text, False
            return
        i = 0
        start = 0
        while i < len(text):
            for ud in self._user_defined:
                if text.startswith(ud, i):
                    if start < i:
                        yield text[start:i], False
                    yield ud, True
                    i += len(ud)
                    start = i
                    break
            else:
                i += 1
        if start < len(text):
            yield text[start:], False

    # ------------------------------------------------------------------ decode
    def decode(self, ids: Iterable[int]) -> str:
        out: List[object] = []  # str pieces and int bytes, in order
        for i in ids:
            i = int(i)
            if not 0 <= i < len(self.pieces):
                continue
            t = self.piece_type[i]
            if t in (_CONTROL, _UNKNOWN):
                continue
            p = self.id_to_piece[i]
            if t == _BYTE:
                out.append(int(p[3:5], 16))
            else:
                out.append(p)

        # fuse byte runs, decode utf-8, join pieces
        text_parts: List[str] = []
        run: List[int] = []
        for item in out:
            if isinstance(item, int):
                run.append(item)
            else:
                if run:
                    text_parts.append(bytes(run).decode("utf-8", "replace"))
                    run = []
                text_parts.append(item)
        if run:
            text_parts.append(bytes(run).decode("utf-8", "replace"))
        text = "".join(text_parts).replace(_SPACE, " ")
        if self.add_dummy_prefix and text.startswith(" "):
            text = text[1:]
        return text


def write_model_proto(pieces: List[Tuple[str, float, int]],
                      model_type: int = 2, add_dummy_prefix: bool = True,
                      unk_id: int = 0, bos_id: int = 1, eos_id: int = 2,
                      pad_id: int = -1) -> bytes:
    """Serialize a minimal ``ModelProto`` (the inverse of
    :func:`parse_model_proto`). Used by tests to build tiny models and by
    anyone who wants to ship a locally-trained SP-BPE vocabulary."""
    def varint(v: int) -> bytes:
        if v < 0:
            v += 1 << 64
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            out.append(b | (0x80 if v else 0))
            if not v:
                return bytes(out)

    def field(num: int, wt: int, payload: bytes) -> bytes:
        return varint(num << 3 | wt) + payload

    buf = bytearray()
    for piece, score, ptype in pieces:
        body = field(1, 2, varint(len(piece.encode())) + piece.encode())
        body += field(2, 5, struct.pack("<f", score))
        body += field(3, 0, varint(ptype))
        buf += field(1, 2, varint(len(body)) + body)
    trainer = (field(3, 0, varint(model_type)) + field(40, 0, varint(unk_id))
               + field(41, 0, varint(bos_id)) + field(42, 0, varint(eos_id))
               + field(43, 0, varint(pad_id)))
    buf += field(2, 2, varint(len(trainer)) + trainer)
    norm = field(3, 0, varint(1 if add_dummy_prefix else 0))
    buf += field(3, 2, varint(len(norm)) + norm)
    return bytes(buf)
