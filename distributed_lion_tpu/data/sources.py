"""Token sources and the batch iterator feeding the train loop.

The reference trains on HF-hub datasets (openwebtext, run_clm.py:316-381;
stack-exchange-paired, sft_llama2.py:99-138). Zero-egress equivalents:

- :func:`synthetic_lm_dataset` — a learnable synthetic language (Markov-ish
  integer sequences) for tests/benchmarks;
- :func:`tokens_from_text_files` — local text → ByteTokenizer/HF-cache →
  ``group_texts`` blocks;
- :class:`TokenDataset` — pre-tokenized ``.npy``/``.bin`` (uint16/uint32
  memmap) block datasets, the standard offline-pretraining format.

All produce [n, block] int32 arrays consumed by :func:`batch_iterator`,
which handles epoch shuffling, per-worker sharding (each data-parallel rank
sees a distinct shard — the reference gets this from HF Trainer's
DistributedSampler), and drop-last batching.
"""

from __future__ import annotations

import pathlib
from dataclasses import dataclass
from typing import Iterator, Sequence

import numpy as np

from distributed_lion_tpu.data.packing import group_texts
from distributed_lion_tpu.data.tokenizer import load_tokenizer


def synthetic_lm_dataset(
    n_blocks: int, block_size: int, vocab_size: int, seed: int = 0
) -> np.ndarray:
    """Sequences with short-range structure (next ≈ prev + small noise mod V)
    so a real LM's loss falls measurably below uniform."""
    rng = np.random.default_rng(seed)
    start = rng.integers(0, vocab_size, size=(n_blocks, 1))
    steps = rng.integers(-2, 3, size=(n_blocks, block_size - 1))
    toks = np.concatenate([start, steps], axis=1).cumsum(axis=1) % vocab_size
    return toks.astype(np.int32)


def tokens_from_text_files(
    paths: Sequence[str | pathlib.Path],
    block_size: int,
    tokenizer_name: str | None = None,
) -> np.ndarray:
    tok = load_tokenizer(tokenizer_name)
    docs = []
    for p in paths:
        text = pathlib.Path(p).read_text(encoding="utf-8", errors="replace")
        docs.append(tok.encode(text, add_eos=True))
    return group_texts(docs, block_size)


@dataclass
class TokenDataset:
    """Memory-mapped pre-tokenized dataset cut into fixed blocks."""

    blocks: np.ndarray  # [n, block_size] int32 (or memmap view)

    @staticmethod
    def from_bin(path: str | pathlib.Path, block_size: int, dtype=np.uint16) -> "TokenDataset":
        flat = np.memmap(path, dtype=dtype, mode="r")
        n = len(flat) // block_size
        return TokenDataset(flat[: n * block_size].reshape(n, block_size))

    @staticmethod
    def from_npy(path: str | pathlib.Path) -> "TokenDataset":
        return TokenDataset(np.load(path, mmap_mode="r"))

    def __len__(self) -> int:
        return len(self.blocks)


class BatchIterator:
    """[global_batch, block] int32 batches, reshuffled each epoch, drop-last.
    ``epochs=None`` cycles forever (step-based training).

    :meth:`skip` fast-forwards by index arithmetic only — O(epochs·n) cheap
    permutation draws, ZERO data reads/copies — so resuming a long run does
    not replay every consumed batch through memory (VERDICT r1 weak #7).
    Deterministic: skip(k) then next() yields exactly what the (k+1)-th
    next() of a fresh iterator would."""

    def __init__(self, blocks: np.ndarray, global_batch: int, *,
                 seed: int = 0, epochs: int | None = None,
                 shuffle: bool = True):
        self._blocks = blocks
        self._gb = int(global_batch)
        n = len(blocks)
        if n < self._gb:
            raise ValueError(f"dataset has {n} blocks < global batch {global_batch}")
        self._n = n
        self._rng = np.random.default_rng(seed)
        self._epochs = epochs
        self._shuffle = shuffle
        self._epoch = 0
        self._order: np.ndarray | None = None
        self._i = 0

    def __iter__(self) -> "BatchIterator":
        return self

    def _ensure_order(self) -> None:
        if self._order is None:
            self._order = (self._rng.permutation(self._n) if self._shuffle
                           else np.arange(self._n))
            self._i = 0

    def _advance_epoch(self) -> None:
        self._epoch += 1
        self._order = None

    def __next__(self) -> np.ndarray:
        while True:
            if self._epochs is not None and self._epoch >= self._epochs:
                raise StopIteration
            self._ensure_order()
            if self._i + self._gb <= self._n:
                idx = self._order[self._i : self._i + self._gb]
                self._i += self._gb
                return np.ascontiguousarray(self._blocks[idx]).astype(np.int32)
            self._advance_epoch()

    def skip(self, k: int) -> None:
        """Fast-forward ``k`` batches without touching the data."""
        while k > 0:
            if self._epochs is not None and self._epoch >= self._epochs:
                return
            self._ensure_order()
            avail = (self._n - self._i) // self._gb
            take = min(k, avail)
            self._i += take * self._gb
            k -= take
            if (self._n - self._i) < self._gb:
                self._advance_epoch()


def batch_iterator(
    blocks: np.ndarray,
    global_batch: int,
    *,
    seed: int = 0,
    epochs: int | None = None,
    shuffle: bool = True,
) -> Iterator[np.ndarray]:
    """See :class:`BatchIterator` (kept as the call-site spelling)."""
    return BatchIterator(blocks, global_batch, seed=seed, epochs=epochs,
                         shuffle=shuffle)
