"""Token sources and the batch iterator feeding the train loop.

The reference trains on HF-hub datasets (openwebtext, run_clm.py:316-381;
stack-exchange-paired, sft_llama2.py:99-138). Zero-egress equivalents:

- :func:`synthetic_lm_dataset` — a learnable synthetic language (Markov-ish
  integer sequences) for tests/benchmarks;
- :func:`tokens_from_text_files` — local text → ByteTokenizer/HF-cache →
  ``group_texts`` blocks;
- :class:`TokenDataset` — pre-tokenized ``.npy``/``.bin`` (uint16/uint32
  memmap) block datasets, the standard offline-pretraining format.

All produce [n, block] int32 arrays consumed by :func:`batch_iterator`,
which handles epoch shuffling, per-worker sharding (each data-parallel rank
sees a distinct shard — the reference gets this from HF Trainer's
DistributedSampler), and drop-last batching.
"""

from __future__ import annotations

import pathlib
from dataclasses import dataclass
from typing import Iterator, Sequence

import numpy as np

from distributed_lion_tpu.data.packing import group_texts
from distributed_lion_tpu.data.tokenizer import load_tokenizer


def synthetic_lm_dataset(
    n_blocks: int, block_size: int, vocab_size: int, seed: int = 0
) -> np.ndarray:
    """Sequences with short-range structure (next ≈ prev + small noise mod V)
    so a real LM's loss falls measurably below uniform."""
    rng = np.random.default_rng(seed)
    start = rng.integers(0, vocab_size, size=(n_blocks, 1))
    steps = rng.integers(-2, 3, size=(n_blocks, block_size - 1))
    toks = np.concatenate([start, steps], axis=1).cumsum(axis=1) % vocab_size
    return toks.astype(np.int32)


def tokens_from_text_files(
    paths: Sequence[str | pathlib.Path],
    block_size: int,
    tokenizer_name: str | None = None,
) -> np.ndarray:
    tok = load_tokenizer(tokenizer_name)
    docs = []
    for p in paths:
        text = pathlib.Path(p).read_text(encoding="utf-8", errors="replace")
        docs.append(tok.encode(text, add_eos=True))
    return group_texts(docs, block_size)


@dataclass
class TokenDataset:
    """Memory-mapped pre-tokenized dataset cut into fixed blocks."""

    blocks: np.ndarray  # [n, block_size] int32 (or memmap view)

    @staticmethod
    def from_bin(path: str | pathlib.Path, block_size: int, dtype=np.uint16) -> "TokenDataset":
        flat = np.memmap(path, dtype=dtype, mode="r")
        n = len(flat) // block_size
        return TokenDataset(flat[: n * block_size].reshape(n, block_size))

    @staticmethod
    def from_npy(path: str | pathlib.Path) -> "TokenDataset":
        return TokenDataset(np.load(path, mmap_mode="r"))

    def __len__(self) -> int:
        return len(self.blocks)


def batch_iterator(
    blocks: np.ndarray,
    global_batch: int,
    *,
    seed: int = 0,
    epochs: int | None = None,
    shuffle: bool = True,
) -> Iterator[np.ndarray]:
    """Yield [global_batch, block] int32 batches, reshuffled each epoch,
    drop-last. ``epochs=None`` cycles forever (step-based training)."""
    n = len(blocks)
    if n < global_batch:
        raise ValueError(f"dataset has {n} blocks < global batch {global_batch}")
    rng = np.random.default_rng(seed)
    epoch = 0
    while epochs is None or epoch < epochs:
        order = rng.permutation(n) if shuffle else np.arange(n)
        for i in range(0, n - global_batch + 1, global_batch):
            idx = order[i : i + global_batch]
            yield np.ascontiguousarray(blocks[idx]).astype(np.int32)
        epoch += 1
