"""ctypes front-end for the C++ prefetching token loader.

Same batch contract as :func:`distributed_lion_tpu.data.sources.batch_iterator`
([global_batch, block] int32, per-epoch reshuffle, drop-last) but the gather
and shuffle run in a C++ background thread over mmap'd shards, overlapping
host input with the TPU step — the framework-native stand-in for the
reference's HF-datasets worker processes (run_clm.py:316-381).
"""

from __future__ import annotations

import ctypes
import pathlib
import time
from typing import Iterator, Sequence

import numpy as np

from distributed_lion_tpu import native
from distributed_lion_tpu.train import journal

_DTYPES = {np.dtype(np.uint16): 2, np.dtype(np.uint32): 4}

# shard-open retry schedule: transient I/O (flaky NFS/FUSE mounts, a shard
# mid-upload) gets RETRIES attempts with exponential backoff before the
# shard is declared corrupt and SKIPPED (loudly, with a metrics counter) —
# a dead shard must cost its blocks, not the epoch.
SHARD_RETRIES = 3
SHARD_BACKOFF_S = 0.05


class CorruptShardError(OSError):
    """A shard failed validation/open after the retry budget."""


def _validate_shard(path: pathlib.Path, dtype_bytes: int) -> None:
    """Cheap structural checks BEFORE the C++ mmap: readable, token-width
    aligned ("at least one full block across the fleet" stays dl_open's
    check). Raises OSError/CorruptShardError on failure."""
    size = path.stat().st_size
    if size == 0:
        raise CorruptShardError(f"{path}: empty shard")
    if size % dtype_bytes:
        raise CorruptShardError(
            f"{path}: {size} bytes is not a multiple of the {dtype_bytes}"
            "-byte token width (torn write or wrong --bin_dtype)")
    with open(path, "rb") as f:  # readability probe (mmap comes later)
        f.read(dtype_bytes)


class NativeTokenLoader:
    """Mmap'd `.bin` token shards cut into fixed blocks, served by a C++
    prefetch thread. The per-shard tail below one block is dropped (each
    shard is packed independently, the usual sharded-pretraining layout).

    Robustness: every shard is validated (with retry + exponential backoff
    for transient I/O) before the native open; a shard that stays unreadable
    or misaligned is SKIPPED with a loud warning instead of killing the run,
    and the count rides the trainer's strict-JSON metrics stream as
    ``skipped_shards`` (``health_metrics``). Only when EVERY shard is bad
    does construction raise. Caveat: skipping a shard shifts every global
    block index, so a CHECKPOINT-RESUMED run must not proceed over a
    shrunken fleet (the deterministic replay would stream different data)
    — cli/run_clm refuses that combination loudly."""

    def __init__(
        self,
        paths: Sequence[str | pathlib.Path],
        block_size: int,
        dtype=np.uint16,
    ):
        self._lib = native.load()
        self.block_size = int(block_size)
        dtype_bytes = _DTYPES.get(np.dtype(dtype))
        if dtype_bytes is None:
            raise ValueError(f"dtype must be uint16 or uint32, got {dtype}")
        self.skipped_shards: list[str] = []
        self.read_retries = 0
        good: list[str] = []
        last_err: Exception | None = None
        for p in paths:
            path = pathlib.Path(p)
            try:
                _with_retries(lambda: _validate_shard(path, dtype_bytes),
                              on_retry=self._count_retry)
                good.append(str(path))
            except Exception as e:
                last_err = e
                self.skipped_shards.append(str(path))
                journal.emit(
                    f"[native_loader] WARNING: skipping corrupt/unreadable"
                    f" shard {path} after {SHARD_RETRIES + 1} attempts: "
                    f"{e}")
                journal.event("shard_skipped", shard=str(path),
                              error=f"{type(e).__name__}: {e}")
        if not good:
            raise CorruptShardError(
                f"all {len(self.skipped_shards)} shard(s) failed validation;"
                f" last error: {last_err}")
        # the fleet actually served, in order — block indexing is a pure
        # function of this list, so resume-consistency checks compare it
        # against the list recorded at checkpoint time (cli/run_clm)
        self.shards = good
        enc = [s.encode() for s in good]
        arr = (ctypes.c_char_p * len(enc))(*enc)
        self._h = self._lib.dl_open(arr, len(enc), dtype_bytes, self.block_size)
        if not self._h:
            raise OSError(self._lib.dl_last_error().decode())

    def __len__(self) -> int:
        return int(self._lib.dl_num_blocks(self._h))

    def health_metrics(self) -> dict:
        """Loader-health counters for the trainer's metrics stream (strict
        JSON scalars — scripts/validate_metrics.py validates the log).
        ``shard_read_retries`` counts transient-I/O retries during shard
        validation/open (post-open reads are mmap'd — the page cache, not
        the I/O stack, serves them, so open time is where flakiness
        shows)."""
        return {"skipped_shards": len(self.skipped_shards),
                "shard_read_retries": self.read_retries}

    def read_block(self, idx: int) -> np.ndarray:
        out = np.empty(self.block_size, np.int32)
        ok = self._lib.dl_read_block(
            self._h, idx, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        )
        if not ok:
            raise IndexError(self._lib.dl_last_error().decode())
        return out

    def _count_retry(self) -> None:
        self.read_retries += 1
        # shard-retry event into the active run journal (no-op without
        # one): transient input-layer I/O becomes part of the run's
        # timeline instead of a bare counter that only surfaces at the
        # next log cadence
        journal.event("shard_retry", retries=self.read_retries)

    def read_blocks(self, start: int, stop: int) -> np.ndarray:
        return np.stack([self.read_block(i) for i in range(start, stop)])

    def batches(
        self,
        global_batch: int,
        *,
        seed: int = 0,
        shuffle: bool = True,
        prefetch_depth: int = 4,
        epochs: int | None = None,
        block_range: tuple[int, int] | None = None,
    ) -> "_NativeBatches":
        """Return a deferred-start batch iterator ([global_batch, block]
        int32). The C++ prefetch thread launches on the first ``next()``, so
        a ``skip(n)`` call before that (checkpoint-resume seek) is forwarded
        to the native sampler — skipped epochs never draw their shuffle and
        skipped batches never read data. ``epochs=None`` cycles forever;
        ``block_range=(lo, hi)`` samples only that half-open block range
        (validation hold-out)."""
        # eager validation (dl_start itself is deferred to the first next(),
        # and only a successful dl_start marks the loader started — an
        # unconsumed/failed iterator never wedges it)
        lo, hi = block_range if block_range is not None else (0, 0)
        if hi <= 0:
            hi = len(self)
        if lo < 0 or lo >= hi or hi > len(self):
            raise RuntimeError(f"invalid sample range [{lo}, {hi})")
        if global_batch <= 0 or global_batch > hi - lo:
            raise RuntimeError(
                f"global_batch {global_batch} must be in [1, {hi - lo}]")
        return _NativeBatches(
            self, global_batch, seed=seed, shuffle=shuffle,
            prefetch_depth=prefetch_depth, epochs=epochs,
            block_range=block_range,
        )

    def _start(self, global_batch: int, *, seed, shuffle, prefetch_depth,
               epochs, block_range, skip_batches: int) -> Iterator[np.ndarray]:
        lo, hi = block_range if block_range is not None else (0, 0)
        ok = self._lib.dl_start(
            self._h, global_batch, seed, int(shuffle), prefetch_depth,
            0 if epochs is None else int(epochs), lo, hi, int(skip_batches),
        )
        if not ok:
            raise RuntimeError(self._lib.dl_last_error().decode())

        def gen():
            out = np.empty((global_batch, self.block_size), np.int32)
            ptr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
            while self._h and self._lib.dl_next(self._h, ptr):
                yield out.copy()

        return gen()

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.dl_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _with_retries(fn, on_retry=None):
    """Run ``fn`` with the shard retry schedule: SHARD_RETRIES retries with
    exponential backoff starting at SHARD_BACKOFF_S. Structural corruption
    (CorruptShardError) is re-raised immediately — a misaligned file will
    not heal by waiting; only transient I/O earns the backoff."""
    delay = SHARD_BACKOFF_S
    for attempt in range(SHARD_RETRIES + 1):
        try:
            return fn()
        except (CorruptShardError, IndexError):
            # structural corruption / out-of-range: deterministic, no point
            # sleeping on it (and no phantom 'transient retry' counters)
            raise
        except Exception:
            if attempt == SHARD_RETRIES:
                raise
            if on_retry is not None:
                on_retry()
            time.sleep(delay)
            delay *= 2


class _NativeBatches:
    """Deferred-start iterator over a :class:`NativeTokenLoader`: records
    ``skip(n)`` calls until the first ``next()``, then starts the C++
    prefetch thread with the accumulated offset."""

    def __init__(self, loader: NativeTokenLoader, global_batch: int, **kwargs):
        self._loader = loader
        self._gb = global_batch
        self._kwargs = kwargs
        self._skip = 0
        self._gen = None

    def skip(self, n: int) -> None:
        if self._gen is not None:
            raise RuntimeError("cannot skip after iteration started")
        self._skip += int(n)

    def health_metrics(self) -> dict:
        """Forwarded loader-health counters — the trainer merges them into
        its metrics stream when the train iterator exposes this hook."""
        return self._loader.health_metrics()

    def __iter__(self) -> "_NativeBatches":
        return self

    def __next__(self) -> np.ndarray:
        if self._gen is None:
            self._gen = self._loader._start(self._gb, skip_batches=self._skip,
                                            **self._kwargs)
        return next(self._gen)


def native_available() -> bool:
    return native.available()
