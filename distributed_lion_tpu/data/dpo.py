"""DPO data prep: prompt/chosen/rejected triples with completion masks.

Intended semantics of the reference's (broken) dpo_llama2.py:
- prompt template "Question: ...\\n\\nAnswer: " (:84-125, return_prompt_and_responses);
- records come from stack-exchange-paired with response_j (chosen) /
  response_k (rejected);
- length filtering: drop samples where prompt+response exceeds max_length or
  prompt exceeds max_prompt_length (:158-168; defaults 1024/512, :51-52);
- sanity_check truncation to 1000 samples (:62, :110-111).

Output: fixed-shape [N, max_length] int32 token arrays + bool masks over
completion tokens (prompt and padding excluded from the DPO logprobs).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def return_prompt_and_responses(sample: dict) -> dict:
    """dpo_llama2.py:91-103 template."""
    return {
        "prompt": f"Question: {sample['question']}\n\nAnswer: ",
        "chosen": sample["response_j"],
        "rejected": sample["response_k"],
    }


def prepare_dpo_batch(
    records: Sequence[dict],
    tokenizer,
    *,
    max_length: int = 1024,
    max_prompt_length: int = 512,
    sanity_check: bool = False,
) -> dict:
    """Tokenize + length-filter + pad to fixed shapes.

    Returns {"chosen", "rejected": [N, max_length] int32,
             "chosen_mask", "rejected_mask": [N, max_length] bool}.
    """
    if sanity_check:  # dpo_llama2.py:110-111
        records = list(records)[:1000]
    pad = getattr(tokenizer, "pad_id", 0)
    eos = getattr(tokenizer, "eos_id", 0)

    rows = {"chosen": [], "rejected": [], "chosen_mask": [], "rejected_mask": []}
    for rec in records:
        trip = return_prompt_and_responses(rec)
        p_ids = tokenizer.encode(trip["prompt"])
        if len(p_ids) > max_prompt_length:  # dpo_llama2.py:158-168
            continue
        keep = True
        encoded = {}
        for side in ("chosen", "rejected"):
            r_ids = tokenizer.encode(trip[side]) + [eos]
            if len(p_ids) + len(r_ids) > max_length:
                keep = False
                break
            ids = p_ids + r_ids
            mask = [False] * len(p_ids) + [True] * len(r_ids)
            ids = ids + [pad] * (max_length - len(ids))
            mask = mask + [False] * (max_length - len(mask))
            encoded[side] = (ids, mask)
        if not keep:
            continue
        for side in ("chosen", "rejected"):
            ids, mask = encoded[side]
            rows[side].append(ids)
            rows[f"{side}_mask"].append(mask)

    if not rows["chosen"]:
        raise ValueError("no DPO samples survived length filtering")
    return {
        "chosen": np.asarray(rows["chosen"], np.int32),
        "rejected": np.asarray(rows["rejected"], np.int32),
        "chosen_mask": np.asarray(rows["chosen_mask"], bool),
        "rejected_mask": np.asarray(rows["rejected_mask"], bool),
    }


def dpo_batch_iterator(batch_data: dict, global_batch: int, *, seed: int = 0):
    """Shuffle-and-cycle iterator over the fixed-shape DPO arrays, yielding
    pytree batches for the Trainer."""
    n = len(batch_data["chosen"])
    if n < global_batch:
        raise ValueError(f"{n} DPO pairs < global batch {global_batch}")
    rng = np.random.default_rng(seed)
    while True:
        order = rng.permutation(n)
        for i in range(0, n - global_batch + 1, global_batch):
            idx = order[i : i + global_batch]
            yield {k: np.ascontiguousarray(v[idx]) for k, v in batch_data.items()}
