"""Native reader for HF fast-tokenizer ``tokenizer.json`` files (BPE models).

Llama-3/Mistral-class checkpoints ship their tokenizer as a single
``tokenizer.json`` (the HF ``tokenizers`` serialization) instead of
SentencePiece's ``tokenizer.model``. The reference reaches these through
``AutoTokenizer`` (/root/reference/sft_llama2.py:157-158); this module reads
the file directly so a local checkpoint tokenizes with its true vocabulary
(128256 for Llama-3) with no HF cache.

Supported shape — the one Llama-3/GPT-2/Qwen-class models actually use:

- ``model.type == "BPE"`` with ``vocab`` (token→id) + ranked ``merges``;
- byte-level alphabet (the GPT-2 byte→unicode table, shared with data.bpe);
- pre-tokenization: the regex from a ``Split`` pre-tokenizer (tiktoken-style
  pattern, compiled with the ``regex`` module) and/or ``ByteLevel``; a
  ``Sequence`` of those is walked recursively;
- ``added_tokens`` (specials like ``<|begin_of_text|>``) matched greedily
  before pre-tokenization, never split.

Token-for-token parity with the ``tokenizers`` library on this shape is
pinned by tests/test_llama_tokenizer.py. Anything structurally outside it
(WordPiece/Unigram models, Metaspace pre-tokenizers, normalizers that
rewrite text) raises loudly instead of tokenizing wrong.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, List, Optional

from distributed_lion_tpu.data.bpe import (
    BPETokenizer,
    bytes_to_unicode,
    unicode_to_bytes,
)

try:
    import regex as _re
except ImportError:  # pragma: no cover
    _re = None

# GPT-2's pattern, the ByteLevel pre-tokenizer's built-in default
# (used when use_regex=true and no Split supplies one)
_BYTELEVEL_PAT = (r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+|"""
                  r""" ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+""")


def _collect_pretokenizers(pt: Optional[dict], out: List[dict]) -> None:
    if pt is None:
        return
    t = pt.get("type")
    if t == "Sequence":
        for sub in pt.get("pretokenizers", []):
            _collect_pretokenizers(sub, out)
    else:
        out.append(pt)


class TokenizerJSON:
    """Byte-level BPE driven by a ``tokenizer.json`` file.

    API-compatible with data.tokenizer.ByteTokenizer (vocab_size,
    bos/eos/pad ids, encode/decode).
    """

    def __init__(self, spec: dict):
        if _re is None:
            raise RuntimeError("the `regex` module is required")
        model = spec.get("model") or {}
        if model.get("type") != "BPE":
            raise ValueError(
                f"unsupported tokenizer.json model type {model.get('type')!r} "
                "(only BPE is implemented)"
            )
        if spec.get("normalizer") is not None:
            raise ValueError(
                "tokenizer.json has a normalizer; this reader supports the "
                "byte-level-BPE shape (Llama-3/GPT-2) which has none"
            )
        self.vocab: dict = dict(model["vocab"])
        pairs = [tuple(m.split(" ", 1)) if isinstance(m, str) else tuple(m)
                 for m in (model.get("merges") or [])]
        self.ranks = {p: i for i, p in enumerate(pairs)}

        pres: List[dict] = []
        _collect_pretokenizers(spec.get("pre_tokenizer"), pres)
        pattern = None
        add_prefix_space = False
        byte_level = False
        for pt in pres:
            t = pt["type"]
            if t == "Split":
                pat = pt.get("pattern", {})
                pattern = pat.get("Regex") if isinstance(pat, dict) else None
                if pattern is None:
                    raise ValueError("Split pre-tokenizer without a Regex "
                                     "pattern is not supported")
                if pt.get("invert"):
                    raise ValueError("inverted Split is not supported")
            elif t == "ByteLevel":
                byte_level = True
                add_prefix_space = bool(pt.get("add_prefix_space", False))
                if pt.get("use_regex", True) and pattern is None:
                    pattern = _BYTELEVEL_PAT
            else:
                raise ValueError(f"unsupported pre-tokenizer {t!r}")
        if not byte_level:
            raise ValueError("only byte-level BPE tokenizer.json files are "
                             "supported (no ByteLevel pre-tokenizer found)")
        self._pat = _re.compile(pattern) if pattern else None
        self._add_prefix_space = add_prefix_space

        self.added: dict = {}  # content -> id
        self.special_ids: set = set()
        for at in spec.get("added_tokens", []):
            self.added[at["content"]] = int(at["id"])
            if at.get("special"):
                self.special_ids.add(int(at["id"]))
            self.vocab.setdefault(at["content"], int(at["id"]))
        # one alternation, longest first (same-position ties go to the
        # earlier alternative, so longest-match greediness is preserved) —
        # NOT a per-character startswith scan over |added| tokens
        self._added_re = _re.compile(
            "|".join(_re.escape(t)
                     for t in sorted(self.added, key=len, reverse=True))
        ) if self.added else None
        self._added_ids = set(self.added.values())

        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        self._b2u = bytes_to_unicode()
        self._u2b = unicode_to_bytes()
        # the merge loop (and its C++ native core) live in BPETokenizer;
        # specials=[] because added tokens are handled here, before BPE
        self._core = BPETokenizer(self.vocab, pairs, specials=[])

        def find(*names):
            for n in names:
                if n in self.added:
                    return self.added[n]
            return None

        self.bos_id = find("<|begin_of_text|>", "<s>", "<|endoftext|>")
        self.eos_id = find("<|end_of_text|>", "<|eot_id|>", "</s>",
                           "<|endoftext|>")
        if self.eos_id is None:
            self.eos_id = self.bos_id if self.bos_id is not None else 0
        if self.bos_id is None:
            self.bos_id = self.eos_id
        pad = find("<pad>", "<|finetune_right_pad_id|>")
        self.pad_id = pad if pad is not None else self.eos_id

    @classmethod
    def load(cls, path: str) -> "TokenizerJSON":
        """``path``: a ``tokenizer.json`` file or a directory holding one."""
        if os.path.isdir(path):
            path = os.path.join(path, "tokenizer.json")
        with open(path, encoding="utf-8") as f:
            return cls(json.load(f))

    @property
    def vocab_size(self) -> int:
        return max(len(self.vocab), 1 + max(self.vocab.values(), default=0))

    # ------------------------------------------------------------------ codec
    def _encode_chunk(self, text: str, ids: List[int]) -> None:
        """Pre-tokenize with OUR pattern, merge via the shared BPETokenizer
        machinery (C++ native core when buildable, its cached Python merge
        loop otherwise)."""
        if not text:
            return
        pretoks = self._pat.findall(text) if self._pat else [text]
        core = self._core._native_core()
        if core is not None:
            ids.extend(
                core.encode_pretoks([t.encode("utf-8") for t in pretoks])
                .tolist())
            return
        for tok in pretoks:
            mapped = "".join(self._b2u[b] for b in tok.encode("utf-8"))
            for piece in self._core._bpe(mapped):
                ids.append(self.vocab[piece])

    def encode(self, text: str, add_bos: bool = False,
               add_eos: bool = False) -> List[int]:
        if self._add_prefix_space and text and not text.startswith(" "):
            text = " " + text
        ids: List[int] = [self.bos_id] if add_bos else []
        # added tokens match greedily before pre-tokenization
        start = 0
        if self._added_re is not None:
            for m in self._added_re.finditer(text):
                self._encode_chunk(text[start:m.start()], ids)
                ids.append(self.added[m.group()])
                start = m.end()
        self._encode_chunk(text[start:], ids)
        if add_eos:
            ids.append(self.eos_id)
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        # NB: no prefix-space stripping — the `tokenizers` ByteLevel decoder
        # maps chars back to bytes verbatim, so decode(encode(' x')) keeps
        # the genuine leading space and round-trips
        parts: List[str] = []
        for i in ids:
            i = int(i)
            if i in self.special_ids or i not in self.inv_vocab:
                continue
            tok = self.inv_vocab[i]
            if i in self._added_ids:
                parts.append(tok)
            else:
                parts.append(bytes(self._u2b[c] for c in tok if c in self._u2b)
                             .decode("utf-8", "replace"))
        return "".join(parts)
